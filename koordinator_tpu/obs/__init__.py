"""koordtrace + koordcost: the observability plane
(docs/OBSERVABILITY.md).

The pieces:
  * `obs.trace` — the bounded span tracer threaded through
    `SchedulerService` cycles (host spans),
  * `obs.phases` — the shared phase-name table every span /
    named_scope label comes from (koordlint OB001 enforces it),
  * `obs.export` — chrome|jsonl|prom rendering of a span buffer plus
    the metrics registry,
  * `obs.hloattrib` — the shared HLO op_name -> phase parser the
    sampled-time and static-cost views both join through,
  * `obs.costmodel` — registry-walking static cost/memory accounting
    (tools/costcheck.py gates it against perf/COST_BASELINE.json),
  * `obs.memwatch` / `obs.slo` — runtime device-memory telemetry with
    the leak sentinel, and multi-window SLO error-budget burn rates
    (surfaced via SchedulerService.health()).

costmodel/memwatch/slo are deliberately NOT imported here: costmodel
pulls jax and the contract registry at import, and the obs package
must stay cheap to import from device-free tooling — consumers import
the submodules they need.

`phase(name)` is THE way kernel code opens a named region: a
`jax.named_scope` whose label is validated against the table, so
device-side profiler streams and host-side spans can never drift
apart. named_scope is pure metadata (it only names HLO ops) — it
cannot perturb shapes, pads, or placement results, which is why the
koordshape/koordpad gates stay untouched by annotation.
"""

from koordinator_tpu.obs import phases  # noqa: F401
from koordinator_tpu.obs.phases import ALL_PHASES, check_phase  # noqa: F401
from koordinator_tpu.obs.trace import (  # noqa: F401
    NOOP_SPAN, SpanRecord, Tracer, jsonl_record,
)


def phase(name: str):
    """A validated `jax.named_scope` for one kernel phase region.

    Raises ValueError on a name missing from obs/phases.py (the
    runtime complement of koordlint OB001). Import of jax is deferred
    so the obs package stays importable in device-free tooling.
    """
    import jax

    return jax.named_scope(check_phase(name))
