"""koordtrace: the observability plane (docs/OBSERVABILITY.md).

Three pieces:
  * `obs.trace` — the bounded span tracer threaded through
    `SchedulerService` cycles (host spans),
  * `obs.phases` — the shared phase-name table every span /
    named_scope label comes from (koordlint OB001 enforces it),
  * `obs.export` — chrome|jsonl|prom rendering of a span buffer plus
    the metrics registry.

`phase(name)` is THE way kernel code opens a named region: a
`jax.named_scope` whose label is validated against the table, so
device-side profiler streams and host-side spans can never drift
apart. named_scope is pure metadata (it only names HLO ops) — it
cannot perturb shapes, pads, or placement results, which is why the
koordshape/koordpad gates stay untouched by annotation.
"""

from koordinator_tpu.obs import phases  # noqa: F401
from koordinator_tpu.obs.phases import ALL_PHASES, check_phase  # noqa: F401
from koordinator_tpu.obs.trace import (  # noqa: F401
    NOOP_SPAN, SpanRecord, Tracer, jsonl_record,
)


def phase(name: str):
    """A validated `jax.named_scope` for one kernel phase region.

    Raises ValueError on a name missing from obs/phases.py (the
    runtime complement of koordlint OB001). Import of jax is deferred
    so the obs package stays importable in device-free tooling.
    """
    import jax

    return jax.named_scope(check_phase(name))
