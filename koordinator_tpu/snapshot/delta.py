"""Device-side snapshot deltas: per-node metric ingest and pod
forget/un-assume, without re-uploading full columns.

The reference keeps its scheduler caches fresh incrementally: informer
event handlers patch NodeInfo/nodeMetric entries in place, and
scheduler_adapter's assume/forget compensates optimistic assumptions when
a bind fails (pkg/scheduler/frameworkext/scheduler_adapter.go; SURVEY §7
hard part (e) — snapshot freshness inside the cycle budget).

TPU design: a delta is a small fixed-capacity struct (K rows, padded with
idx = -1) uploaded per ingest tick; application is ONE jitted scatter
program over the device-resident snapshot, so a 10k-node cluster's metric
churn costs an O(K) transfer + O(K) scatter instead of an O(N) rebuild
and re-upload. Fixed K means repeated ingests reuse one compiled program.
"""

from __future__ import annotations

import enum
import functools
from typing import Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.extension import ResourceKind as _RK
from koordinator_tpu.snapshot.schema import (
    Array,
    ClusterSnapshot,
    register_struct,
    shape_contract,
)

_CPU = int(_RK.CPU)

__all__ = ["NodeMetricDelta", "NodeTopologyDelta", "DeltaRejectReason",
           "apply_metric_delta", "apply_topology_delta", "delta_version",
           "forget_pods"]


class DeltaRejectReason(enum.Enum):
    """Why the store's version guard refused to apply a delta — the
    typed reason surfaced to metrics (scheduler_delta_rejected) and to
    `SnapshotStore.take_delta_rejection`."""

    STALE_VERSION = "stale_version"          # version < last applied
    DUPLICATE_VERSION = "duplicate_version"  # version == last applied


def delta_version(delta) -> Optional[int]:
    """Host-side read of a delta's source version; None = unversioned
    (legacy producers and the sidecar wire format), which always
    applies. Deltas are built host-side, so this never syncs a
    device value."""
    v = getattr(delta, "source_version", None)
    if v is None:
        return None
    return int(np.asarray(v))


@flax.struct.dataclass
class NodeMetricDelta:
    """K node rows of metric-derived columns (builder.metric_delta output);
    idx = -1 rows are padding and apply nowhere.

    `source_version` is the producer's monotonically increasing delta
    sequence number (builder stamps it per emission; None = unversioned).
    The STORE, not the apply kernel, enforces ordering: a delta whose
    version is <= the last applied one is an out-of-order or duplicate
    replay and no-ops idempotently with a typed reason
    (DeltaRejectReason) — silently re-applying it would scatter stale
    rows over fresher ones."""

    idx: Array                       # i32[K] node row, -1 = pad
    metric_fresh: Array              # bool[K]
    usage: Array                     # f32[K, R]
    prod_usage: Array                # f32[K, R]
    agg_usage: Array                 # f32[K, NUM_AGG, R]
    has_agg: Array                   # bool[K]
    assigned_estimated: Array        # f32[K, R]
    assigned_correction: Array       # f32[K, R]
    prod_assigned_estimated: Array   # f32[K, R]
    prod_assigned_correction: Array  # f32[K, R]
    source_version: Array = None     # i32[] producer sequence, None = unversioned


register_struct(NodeMetricDelta, {
    "idx": "i32[K~pad:-1]",
    "metric_fresh": "bool[K~pad:false]",
    "usage": "f32[K~pad:zero,R]",
    "prod_usage": "f32[K~pad:zero,R]",
    "agg_usage": "f32[K~pad:zero,AGG,R]",
    "has_agg": "bool[K~pad:false]",
    "assigned_estimated": "f32[K~pad:zero,R]",
    "assigned_correction": "f32[K~pad:zero,R]",
    "prod_assigned_estimated": "f32[K~pad:zero,R]",
    "prod_assigned_correction": "f32[K~pad:zero,R]",
    "source_version": "?i32[]",
})


@shape_contract(snap="ClusterSnapshot", delta="NodeMetricDelta",
                _returns="ClusterSnapshot",
                _pad="idx -1 rows are padding and scatter to the drop row")
@jax.jit
def apply_metric_delta(snap: ClusterSnapshot,
                       delta: NodeMetricDelta) -> ClusterSnapshot:
    """Scatter the delta rows into the node columns (replace semantics —
    each row is that node's full recomputed metric view, exactly what the
    full rebuild would have produced for it)."""
    nodes = snap.nodes
    n = nodes.num_nodes
    tgt = jnp.where(delta.idx >= 0, delta.idx, n)

    def put(col, rows):
        return col.at[tgt].set(rows, mode="drop")

    nodes = nodes.replace(
        metric_fresh=put(nodes.metric_fresh, delta.metric_fresh),
        usage=put(nodes.usage, delta.usage),
        prod_usage=put(nodes.prod_usage, delta.prod_usage),
        agg_usage=put(nodes.agg_usage, delta.agg_usage),
        has_agg=put(nodes.has_agg, delta.has_agg),
        assigned_estimated=put(nodes.assigned_estimated,
                               delta.assigned_estimated),
        assigned_correction=put(nodes.assigned_correction,
                                delta.assigned_correction),
        prod_assigned_estimated=put(nodes.prod_assigned_estimated,
                                    delta.prod_assigned_estimated),
        prod_assigned_correction=put(nodes.prod_assigned_correction,
                                     delta.prod_assigned_correction),
    )
    return snap.replace(nodes=nodes, version=snap.version + 1)


@flax.struct.dataclass
class NodeTopologyDelta:
    """K node rows of IDENTITY columns — the append/compact delta for
    node add/remove/update churn (VERDICT r3 #7). The reference's
    informers absorb node churn incrementally (frameworkext/
    informers.go event handlers patching the cache); here each row is
    the node's complete recomputed identity view, scattered into the
    padded column capacity, so scale-up/down of K nodes costs an O(K)
    transfer instead of the O(N) rebuild + ~10 s full publish.

    A REMOVED node is simply a zeroed row (schedulable=False,
    allocatable=0, fresh=False): there is no remove flag on the wire.
    The metric columns ride along as a nested NodeMetricDelta sharing
    the same idx (a new node usually has no metric yet — fresh=False).
    Capacity (the padded N) never changes on this path; exhausting it
    falls back to a full rebuild, which may re-bucket.
    """

    idx: Array                # i32[K] node row, -1 = pad
    allocatable: Array        # f32[K, R]
    requested: Array          # f32[K, R] (0 for empty added nodes)
    schedulable: Array        # bool[K]
    label_group: Array        # i32[K]
    taint_group: Array        # i32[K]
    numa_cap: Array           # f32[K, Z, 2]
    numa_free: Array          # f32[K, Z, 2]
    numa_valid: Array         # bool[K, Z]
    numa_policy: Array        # i32[K]
    cpu_amplification: Array  # f32[K]
    # per-node device pools (I instances; zero-capacity axes compile out)
    gpu_total: Array          # f32[K, 3]
    gpu_free: Array           # f32[K, I, 3]
    gpu_valid: Array          # bool[K, I]
    gpu_numa: Array           # i32[K, I]
    gpu_pcie: Array           # i32[K, I]
    aux_free: Array           # f32[K, A, J]
    aux_valid: Array          # bool[K, A, J]
    metric: NodeMetricDelta = None  # same idx; None only pre-init
    source_version: Array = None    # i32[] producer sequence (see
                                    # NodeMetricDelta.source_version)


register_struct(NodeTopologyDelta, {
    "idx": "i32[K~pad:-1]",
    "allocatable": "f32[K~pad:zero,R]",
    "requested": "f32[K~pad:zero,R]",
    "schedulable": "bool[K~pad:false]",
    "label_group": "i32[K~pad:zero]",
    "taint_group": "i32[K~pad:zero]",
    "numa_cap": "f32[K~pad:zero,Z~pad:zero,2]",
    "numa_free": "f32[K~pad:zero,Z~pad:zero,2]",
    "numa_valid": "bool[K~pad:false,Z~pad:false]",
    "numa_policy": "i32[K~pad:zero]",
    "cpu_amplification": "f32[K~pad:one]",
    "gpu_total": "f32[K~pad:zero,DEV]",
    "gpu_free": "f32[K~pad:zero,I~pad:zero,DEV]",
    "gpu_valid": "bool[K~pad:false,I~pad:false]",
    "gpu_numa": "i32[K~pad:-1,I~pad:-1]",
    "gpu_pcie": "i32[K~pad:-1,I~pad:-1]",
    "aux_free": "f32[K~pad:zero,AX,J~pad:zero]",
    "aux_valid": "bool[K~pad:false,AX,J~pad:false]",
    "metric": "NodeMetricDelta",
    "source_version": "?i32[]",
})


@shape_contract(snap="ClusterSnapshot", delta="NodeTopologyDelta",
                _returns="ClusterSnapshot",
                _pad="idx -1 rows are padding; a removed node is a "
                     "zeroed row, not a remove flag")
@jax.jit
def apply_topology_delta(snap: ClusterSnapshot,
                         delta: NodeTopologyDelta) -> ClusterSnapshot:
    """Scatter the identity rows, then the metric rows (replace
    semantics, like apply_metric_delta: each row is exactly what a full
    rebuild would have produced for that node)."""
    nodes = snap.nodes
    devices = snap.devices
    n = nodes.num_nodes
    tgt = jnp.where(delta.idx >= 0, delta.idx, n)

    def put(col, rows):
        return col.at[tgt].set(rows, mode="drop")

    nodes = nodes.replace(
        allocatable=put(nodes.allocatable, delta.allocatable),
        requested=put(nodes.requested, delta.requested),
        schedulable=put(nodes.schedulable, delta.schedulable),
        label_group=put(nodes.label_group, delta.label_group),
        taint_group=put(nodes.taint_group, delta.taint_group),
        numa_cap=put(nodes.numa_cap, delta.numa_cap),
        numa_free=put(nodes.numa_free, delta.numa_free),
        numa_valid=put(nodes.numa_valid, delta.numa_valid),
        numa_policy=put(nodes.numa_policy, delta.numa_policy),
        cpu_amplification=put(nodes.cpu_amplification,
                              delta.cpu_amplification),
    )
    devices = devices.replace(
        gpu_total=put(devices.gpu_total, delta.gpu_total),
        gpu_free=put(devices.gpu_free, delta.gpu_free),
        gpu_valid=put(devices.gpu_valid, delta.gpu_valid),
        gpu_numa=put(devices.gpu_numa, delta.gpu_numa),
        gpu_pcie=put(devices.gpu_pcie, delta.gpu_pcie),
        aux_free=put(devices.aux_free, delta.aux_free),
        aux_valid=put(devices.aux_valid, delta.aux_valid),
    )
    snap = snap.replace(nodes=nodes, devices=devices)
    return apply_metric_delta(snap, delta.metric)


@shape_contract(snap="ClusterSnapshot", pods="PodBatch",
                result="ScheduleResult", mask="bool[P~pad:false]",
                _pad="un-masked rows and never-assigned rows (assignment "
                     "-1) return nothing; charges scatter to drop rows",
                _returns="ClusterSnapshot")
@functools.partial(jax.jit, static_argnames=("enable_amplification",))
def forget_pods(snap: ClusterSnapshot, pods, result,
                mask: jnp.ndarray,
                enable_amplification: Optional[bool] = None
                ) -> ClusterSnapshot:
    """Un-assume: return the charges of `mask`ed pods from a
    schedule_batch result whose binds failed (scheduler_adapter.go
    Forget). The exact inverse of the post-commit rebuild: node requested
    / quota used / gang assumed / NUMA takes / GPU instances / aux VFs /
    reservation holds all flow back, so a retry sees the capacity again.
    The amplified-CPU reversal follows `result.amplified` (the flag the
    producing schedule_batch ran with) so the CPU returned equals the CPU
    charged; pass `enable_amplification` only to override it.
    """
    from koordinator_tpu.scheduler.plugins import deviceshare

    nodes, quotas, gangs = snap.nodes, snap.quotas, snap.gangs
    resv, devices = snap.reservations, snap.devices
    n = nodes.num_nodes
    n_res = resv.valid.shape[0]
    und = mask & (result.assignment >= 0)
    on_slot = result.res_slot >= 0
    node_tgt = jnp.where(und, result.assignment, n)
    req = pods.requests * und[:, None]

    # node requested: only non-consumers charged it (consumers drew from
    # the reservation). CPU-bind pods on amplified nodes were charged
    # request x ratio (core.py amplified-CPU commit) — return the same.
    # result.amplified is static metadata (pytree_node=False), so plain
    # truthiness is trace-safe; a bool() coercion here would read as a
    # host sync to koordlint (and be one if the field ever went traced)
    amp = enable_amplification
    if amp is None:
        amp = getattr(result, "amplified", False)
    req_node = req
    if amp:
        f_amp = jnp.where(
            und & pods.numa_single,
            nodes.cpu_amplification[jnp.clip(result.assignment, 0, n - 1)],
            1.0)
        req_node = req.at[:, int(_CPU)].mul(f_amp)
    requested = nodes.requested.at[
        jnp.where(und & ~on_slot, result.assignment, n)].add(
            -req_node, mode="drop")
    est = pods.estimated * und[:, None]
    assigned_est = nodes.assigned_estimated.at[node_tgt].add(
        -est, mode="drop")
    is_prod = pods.priority_class == 4
    prod_est = nodes.prod_assigned_estimated.at[node_tgt].add(
        -est * is_prod[:, None], mode="drop")

    n_quotas = quotas.used.shape[0]
    quota_id = jnp.maximum(pods.quota_id, 0)
    depth = quotas.depth_ancestor.shape[1]
    pod_anc = jnp.where(pods.quota_id[:, None] >= 0,
                        quotas.depth_ancestor[quota_id], -1)
    used = quotas.used
    for d in range(depth):
        anc = jnp.where(und, pod_anc[:, d], -1)
        used = used.at[jnp.where(anc >= 0, anc, n_quotas)].add(
            -req, mode="drop")

    n_gangs = gangs.assumed.shape[0]
    assumed = gangs.assumed.at[
        jnp.where(und & (pods.gang_id >= 0), jnp.maximum(pods.gang_id, 0),
                  n_gangs)].add(-1, mode="drop")

    # NUMA takes back to the node's open pool or the reservation hold
    numa_free = jnp.minimum(
        nodes.numa_free.at[
            jnp.where(und & ~on_slot, result.assignment, n)].add(
                result.numa_take * und[:, None, None], mode="drop"),
        nodes.numa_cap)
    slot_tgt = jnp.where(und & on_slot, jnp.maximum(result.res_slot, 0),
                         n_res)
    resv_numa = resv.numa_free.at[slot_tgt].add(
        result.numa_take * und[:, None, None], mode="drop")

    # GPU instances back (per-instance amounts are a pure function of
    # (pod, node), same as the commit used)
    n_inst = devices.gpu_free.shape[1]
    gpu_free, resv_gpu = devices.gpu_free, resv.gpu_free
    if n_inst:
        _, per_f = deviceshare.per_instance_at(devices, pods,
                                               result.assignment)
        g_upd = (result.gpu_take[:, :, None] * per_f[:, None, :]
                 * und[:, None, None])
        gpu_free = devices.gpu_free.at[
            jnp.where(und & ~on_slot, result.assignment, n)].add(
                g_upd, mode="drop")
        resv_gpu = resv.gpu_free.at[slot_tgt].add(g_upd, mode="drop")
    n_aux = devices.aux_free.shape[2]
    aux_free = devices.aux_free
    if n_aux:
        flat = aux_free.reshape(-1, 1)
        n_types = aux_free.shape[1]
        for t in range(n_types):
            a_req = pods.requests[:, deviceshare.AUX_KINDS[t]]
            took = und & (a_req > 0) & (result.aux_inst[:, t] >= 0)
            base = (jnp.maximum(result.assignment, 0) * n_types + t) * n_aux
            seg = jnp.where(took, base + result.aux_inst[:, t],
                            n * n_types * n_aux)
            flat = flat.at[seg].add((a_req * took)[:, None], mode="drop")
        aux_free = flat.reshape(aux_free.shape)

    resv_free = resv.free.at[slot_tgt].add(req, mode="drop")
    # a forgotten AllocateOnce winner re-opens its slot
    reopen = jnp.zeros((n_res,), bool).at[slot_tgt].max(
        und, mode="drop") & resv.allocate_once
    return snap.replace(
        nodes=nodes.replace(requested=jnp.maximum(requested, 0.0),
                            assigned_estimated=jnp.maximum(assigned_est, 0.0),
                            prod_assigned_estimated=jnp.maximum(prod_est, 0.0),
                            numa_free=numa_free),
        quotas=quotas.replace(used=jnp.maximum(used, 0.0)),
        gangs=gangs.replace(assumed=jnp.maximum(assumed, 0)),
        reservations=resv.replace(free=resv_free, numa_free=resv_numa,
                                  gpu_free=resv_gpu,
                                  valid=resv.valid | reopen),
        devices=devices.replace(gpu_free=gpu_free, aux_free=aux_free),
        version=snap.version + 1)
