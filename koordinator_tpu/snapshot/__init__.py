"""Core data plane: columnar cluster state mirrored into device tensors.

The reference's scheduler works off informer caches + a NodeInfo snapshot
(k8s framework); here the equivalent is a versioned, double-buffered
`ClusterSnapshot` pytree of fixed-shape arrays (SURVEY.md 2.9, 7.1).
"""

from koordinator_tpu.snapshot.schema import (  # noqa: F401
    AGG_TYPES,
    ClusterSnapshot,
    GangState,
    NodeState,
    PodBatch,
    QuotaState,
    ReservationState,
)
from koordinator_tpu.snapshot.builder import SnapshotBuilder  # noqa: F401
from koordinator_tpu.snapshot.delta import (  # noqa: F401
    DeltaRejectReason,
    NodeMetricDelta,
    NodeTopologyDelta,
    apply_metric_delta,
    apply_topology_delta,
    delta_version,
    forget_pods,
)
from koordinator_tpu.snapshot.store import SnapshotStore  # noqa: F401
from koordinator_tpu.snapshot.informers import (  # noqa: F401
    ClusterInformerHub,
    SnapshotSyncer,
)
