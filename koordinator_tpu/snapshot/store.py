"""Versioned snapshot store with double-buffered device upload.

The reference keeps informer caches fresh via watch streams and takes an
immutable NodeInfo snapshot per scheduling cycle. Here the host builds a new
columnar snapshot and uploads it asynchronously while the previous version
is still being consumed by in-flight kernels — classic double buffering to
hide HBM transfer latency behind compute (SURVEY.md 2.9) — and between
rebuilds the store stays fresh with O(K) device-side deltas: `ingest`
scatters per-node metric updates, `forget` un-assumes failed binds
(snapshot/delta.py; scheduler_adapter.go assume/forget).

Restart recovery (docs/DESIGN.md "Crash recovery & mesh elasticity"):
`checkpoint`/`restore` persist the full snapshot with its version and
delta high-water mark, atomically (tmp + os.replace) and checksummed,
so a crashed service rehydrates the device snapshot, replays the
producer's versioned deltas through the existing idempotent guard, and
hands the interrupted batch to the commit journal
(scheduler/journal.py).
"""

from __future__ import annotations

import io
import os
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import numpy as np

from koordinator_tpu.snapshot.schema import (
    STRUCT_CLASSES,
    STRUCT_SPECS,
    ClusterSnapshot,
)
from koordinator_tpu.utils.sync import guarded_by

# checkpoint framing: MAGIC, store version, applied delta watermark,
# npz byte length, then crc32 over ALL of the preceding header fields
# plus the npz bytes — the version/watermark are load-bearing for
# recovery (they gate journal-epoch replay and delta dedup), so header
# corruption must be caught exactly like blob corruption
_CK_MAGIC = 0x4B434B31  # "KCK1"
_CK_PREFIX = struct.Struct("<IQQQ")
_CK_CRC = struct.Struct("<I")
_CK_HEADER_SIZE = _CK_PREFIX.size + _CK_CRC.size


def _struct_leaves(name: str, obj,
                   prefix: str = "") -> Iterator[Tuple[str, np.ndarray]]:
    """(dotted key, host array) per registered leaf — the koordshape
    field-spec tables drive serialization exactly like they drive the
    mesh shardings, so a new snapshot field cannot silently be dropped
    from checkpoints."""
    for fname, spec in STRUCT_SPECS[name].items():
        if isinstance(spec, str) and spec in STRUCT_SPECS:
            yield from _struct_leaves(spec, getattr(obj, fname),
                                      prefix + fname + ".")
        elif isinstance(spec, str) and "[" in spec:
            yield prefix + fname, np.asarray(getattr(obj, fname))
        # bare-symbol entries (num_nodes) are properties, not fields


def _build_struct(name: str, arrays: Dict[str, np.ndarray],
                  prefix: str = ""):
    fields = {}
    for fname, spec in STRUCT_SPECS[name].items():
        if isinstance(spec, str) and spec in STRUCT_SPECS:
            fields[fname] = _build_struct(spec, arrays,
                                          prefix + fname + ".")
        elif isinstance(spec, str) and "[" in spec:
            fields[fname] = arrays[prefix + fname]
    return STRUCT_CLASSES[name](**fields)


@guarded_by(
    _current="_lock",
    _version="_lock",
    _applied_delta_version="_lock",
    _last_delta_rejection="_lock",
    delta_rejections="_lock",
    _last_checkpoint_version="_lock",
    # checkpoint serialization: _ck_lock spans capture -> tmp ->
    # os.replace and owns the written-checkpoint counter
    checkpoints_written="_ck_lock",
    _sharding="publish-once",
    checkpoint_path="publish-once",
    checkpoint_every="publish-once",
    crash_hook="publish-once",
)
class SnapshotStore:
    """Holds the current device-resident ClusterSnapshot.

    - `publish(snapshot)` uploads a new version (host numpy pytree) without
      blocking readers; upload overlaps the previous version's compute because
      `jax.device_put` is async.
    - `current()` returns the freshest fully-uploaded version.
    - Optional `sharding` places the node axis across a mesh (parallel/mesh.py).
    """

    def __init__(self, sharding: Optional[Any] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 1,
                 crash_hook: Optional[Callable[[str], None]] = None):
        self._sharding = sharding
        self._lock = threading.Lock()
        self._current: Optional[ClusterSnapshot] = None
        self._version = 0
        # delta replay guard: highest source_version applied since the
        # last full publish (a publish opens a new delta epoch)
        self._applied_delta_version = 0
        self._last_delta_rejection = None
        self.delta_rejections = 0
        # restart recovery (docs/DESIGN.md "Crash recovery & mesh
        # elasticity"): periodic checkpoints of the full snapshot +
        # version + delta watermark; `maybe_checkpoint` is called by
        # owners OUTSIDE their commit locks (disk must never stall a
        # scheduler), at most every `checkpoint_every` versions.
        # `crash_hook` is the kill-injection seam (faults.sigkill_at).
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = max(int(checkpoint_every), 1)
        self.crash_hook = crash_hook
        self._last_checkpoint_version = 0
        self.checkpoints_written = 0
        # serializes whole checkpoint writes (capture -> tmp ->
        # os.replace): without it, racing maybe_checkpoint() callers
        # (publish / ingest / post-schedule all call it, from different
        # threads) would interleave writes into the shared .tmp file or
        # replace a newer checkpoint with an older capture
        self._ck_lock = threading.Lock()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def applied_delta_version(self) -> int:
        with self._lock:
            return self._applied_delta_version

    @property
    def last_checkpoint_version(self) -> int:
        """Store version of the last durable checkpoint (0 = none) —
        the anchor below which journal epochs can never replay
        (CommitJournal.prune)."""
        with self._lock:
            return self._last_checkpoint_version

    def take_delta_rejection(self):
        """Pop the last ingest's DeltaRejectReason (None if it applied)
        — the typed-reason handoff SchedulerService.ingest surfaces to
        the scheduler_delta_rejected metric."""
        with self._lock:
            reason = self._last_delta_rejection
            self._last_delta_rejection = None
            return reason

    def publish(self, snapshot: ClusterSnapshot) -> ClusterSnapshot:
        """Upload a host-built snapshot; returns the device-resident
        pytree. `sharding` may be a single sharding or a pytree of
        shardings matching the snapshot (parallel.snapshot_sharding's
        node-axis layout) — device_put handles either as a prefix."""
        if self._sharding is not None:
            on_device = jax.device_put(snapshot, self._sharding)
        else:
            on_device = jax.device_put(snapshot)
        with self._lock:
            self._version += 1
            self._current = on_device
            # a full publish is a new delta epoch: a restarted producer
            # restarts its sequence at 1 and must not be rejected
            # against a previous epoch's high-water mark
            self._applied_delta_version = 0
            self._last_delta_rejection = None
        return on_device

    def current(self) -> ClusterSnapshot:
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            return self._current

    def update(self, fn: Callable[[ClusterSnapshot], ClusterSnapshot]) -> ClusterSnapshot:
        """Apply a device-side functional update (e.g. post-commit usage
        scatter) and publish the result as the next version."""
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            self._current = fn(self._current)
            self._version += 1
            return self._current

    def ingest(self, delta) -> ClusterSnapshot:
        """Apply a NodeMetricDelta or NodeTopologyDelta device-side
        (snapshot/delta.py): an O(K) upload + scatter instead of an O(N)
        rebuild — the informer event-handler path of the reference, on
        columns. Topology deltas patch node identity (add/remove/update
        rows) within the padded capacity; metric deltas refresh the
        NodeMetric-derived columns.

        Versioned deltas (`source_version` set) are guarded against
        out-of-order / duplicate replay: a version <= the last applied
        one no-ops IDEMPOTENTLY — the snapshot and store version are
        untouched — and the typed reason is held for
        `take_delta_rejection`. Re-applying a stale delta would scatter
        old rows over fresher ones (last-writer-wins per node row), the
        exact mis-apply this guard exists for. Unversioned deltas
        always apply (legacy producers, the sidecar wire)."""
        from koordinator_tpu.snapshot.delta import (
            DeltaRejectReason,
            NodeTopologyDelta,
            apply_metric_delta,
            apply_topology_delta,
            delta_version,
        )

        ver = delta_version(delta)
        if isinstance(delta, NodeTopologyDelta):
            apply = apply_topology_delta
        else:
            apply = apply_metric_delta
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            if ver is not None:
                if ver <= self._applied_delta_version:
                    self._last_delta_rejection = (
                        DeltaRejectReason.DUPLICATE_VERSION
                        if ver == self._applied_delta_version
                        else DeltaRejectReason.STALE_VERSION)
                    self.delta_rejections += 1
                    return self._current
                self._applied_delta_version = ver
            self._last_delta_rejection = None
            self._current = apply(self._current, delta)
            self._version += 1
            return self._current

    # --- restart recovery: periodic checkpoints --------------------------

    def maybe_checkpoint(self) -> bool:
        """Checkpoint when configured and `checkpoint_every` versions
        have landed since the last one. Called by owners OUTSIDE their
        commit locks (SchedulerService calls it after publish/ingest/
        schedule release the lock) so a fsync can never stall a
        scheduling cycle waiting on the lock."""
        if self.checkpoint_path is None:
            return False
        with self._lock:
            due = (self._current is not None
                   and self._version - self._last_checkpoint_version
                   >= self.checkpoint_every)
        if not due:
            return False
        self.checkpoint()
        return True

    def checkpoint(self, path: Optional[str] = None) -> str:
        """Write the current snapshot + version + delta watermark,
        checksummed and ATOMIC (tmp file + os.replace): a crash
        mid-write leaves the previous checkpoint intact, never a torn
        one — `restore` therefore only ever sees a complete file, and
        the crc is the belt to that suspender."""
        path = path or self.checkpoint_path
        if path is None:
            raise ValueError("no checkpoint path configured")
        # one writer at a time, capture THROUGH replace: concurrent
        # maybe_checkpoint() callers otherwise interleave in the shared
        # tmp file, or an older capture can os.replace a newer one
        with self._ck_lock:
            with self._lock:
                snap = self._current
                version = self._version
                delta_v = self._applied_delta_version
            if snap is None:
                raise RuntimeError("no snapshot published yet")
            # serialize outside the SNAPSHOT lock: the D2H gather and
            # npz encode are the expensive part, and readers/writers
            # must not wait on them (only other checkpointers do)
            buf = io.BytesIO()
            np.savez(buf, **dict(_struct_leaves("ClusterSnapshot", snap)))
            blob = buf.getvalue()
            prefix = _CK_PREFIX.pack(_CK_MAGIC, version, delta_v,
                                     len(blob))
            crc = zlib.crc32(blob, zlib.crc32(prefix)) & 0xFFFFFFFF
            header = prefix + _CK_CRC.pack(crc)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(blob[:len(blob) // 2])
                f.flush()
                if self.crash_hook is not None:
                    self.crash_hook("mid_checkpoint")  # SIGKILL = torn
                f.write(blob[len(blob) // 2:])
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            with self._lock:
                self._last_checkpoint_version = version
            self.checkpoints_written += 1
        return path

    def restore(self, path: Optional[str] = None) -> bool:
        """Rehydrate the device snapshot from the last checkpoint:
        version and the delta high-water mark come back with it, so a
        producer replaying its delta log after the restart has every
        already-applied delta no-op idempotently in the version guard
        while later ones apply normally. Returns False (no state
        touched) when there is no readable checkpoint — missing,
        corrupt, or written for a different snapshot schema (field-set
        drift across a deploy) — and the caller falls back to a fresh
        publish."""
        path = path or self.checkpoint_path
        if path is None or not os.path.exists(path):
            return False
        try:
            with open(path, "rb") as f:
                header = f.read(_CK_HEADER_SIZE)
                prefix = header[:_CK_PREFIX.size]
                magic, version, delta_v, blob_len = \
                    _CK_PREFIX.unpack(prefix)
                (crc,) = _CK_CRC.unpack(header[_CK_PREFIX.size:])
                if magic != _CK_MAGIC:
                    return False
                blob = f.read(blob_len)
            if len(blob) != blob_len or \
                    zlib.crc32(blob, zlib.crc32(prefix)) & 0xFFFFFFFF \
                    != crc:
                return False
            arrays = dict(np.load(io.BytesIO(blob)))
            # a crc-valid checkpoint from a build with a DIFFERENT
            # registered field set (schema drift) raises KeyError here:
            # unreadable for this build, same typed outcome as corrupt
            snap = _build_struct("ClusterSnapshot", arrays)
        except (OSError, ValueError, KeyError, struct.error):
            return False
        if self._sharding is not None:
            on_device = jax.device_put(snap, self._sharding)
        else:
            on_device = jax.device_put(snap)
        with self._lock:
            self._current = on_device
            self._version = int(version)
            self._applied_delta_version = int(delta_v)
            self._last_checkpoint_version = int(version)
            self._last_delta_rejection = None
        return True

    def forget(self, pods, result, mask) -> ClusterSnapshot:
        """Un-assume failed binds (scheduler_adapter.go Forget): returns
        the masked pods' charges to the snapshot device-side. The
        amplified-CPU reversal rides `result.amplified`, so callers can't
        mismatch the flag the producing schedule ran with."""
        from koordinator_tpu.snapshot.delta import forget_pods

        return self.update(lambda s: forget_pods(s, pods, result, mask))
