"""Versioned snapshot store with double-buffered device upload.

The reference keeps informer caches fresh via watch streams and takes an
immutable NodeInfo snapshot per scheduling cycle. Here the host builds a new
columnar snapshot and uploads it asynchronously while the previous version
is still being consumed by in-flight kernels — classic double buffering to
hide HBM transfer latency behind compute (SURVEY.md 2.9) — and between
rebuilds the store stays fresh with O(K) device-side deltas: `ingest`
scatters per-node metric updates, `forget` un-assumes failed binds
(snapshot/delta.py; scheduler_adapter.go assume/forget).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from koordinator_tpu.snapshot.schema import ClusterSnapshot


class SnapshotStore:
    """Holds the current device-resident ClusterSnapshot.

    - `publish(snapshot)` uploads a new version (host numpy pytree) without
      blocking readers; upload overlaps the previous version's compute because
      `jax.device_put` is async.
    - `current()` returns the freshest fully-uploaded version.
    - Optional `sharding` places the node axis across a mesh (parallel/mesh.py).
    """

    def __init__(self, sharding: Optional[Any] = None):
        self._sharding = sharding
        self._lock = threading.Lock()
        self._current: Optional[ClusterSnapshot] = None
        self._version = 0

    @property
    def version(self) -> int:
        return self._version

    def publish(self, snapshot: ClusterSnapshot) -> ClusterSnapshot:
        """Upload a host-built snapshot; returns the device-resident
        pytree. `sharding` may be a single sharding or a pytree of
        shardings matching the snapshot (parallel.snapshot_sharding's
        node-axis layout) — device_put handles either as a prefix."""
        if self._sharding is not None:
            on_device = jax.device_put(snapshot, self._sharding)
        else:
            on_device = jax.device_put(snapshot)
        with self._lock:
            self._version += 1
            self._current = on_device
        return on_device

    def current(self) -> ClusterSnapshot:
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            return self._current

    def update(self, fn: Callable[[ClusterSnapshot], ClusterSnapshot]) -> ClusterSnapshot:
        """Apply a device-side functional update (e.g. post-commit usage
        scatter) and publish the result as the next version."""
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            self._current = fn(self._current)
            self._version += 1
            return self._current

    def ingest(self, delta) -> ClusterSnapshot:
        """Apply a NodeMetricDelta or NodeTopologyDelta device-side
        (snapshot/delta.py): an O(K) upload + scatter instead of an O(N)
        rebuild — the informer event-handler path of the reference, on
        columns. Topology deltas patch node identity (add/remove/update
        rows) within the padded capacity; metric deltas refresh the
        NodeMetric-derived columns."""
        from koordinator_tpu.snapshot.delta import (
            NodeTopologyDelta,
            apply_metric_delta,
            apply_topology_delta,
        )

        if isinstance(delta, NodeTopologyDelta):
            return self.update(lambda s: apply_topology_delta(s, delta))
        return self.update(lambda s: apply_metric_delta(s, delta))

    def forget(self, pods, result, mask) -> ClusterSnapshot:
        """Un-assume failed binds (scheduler_adapter.go Forget): returns
        the masked pods' charges to the snapshot device-side. The
        amplified-CPU reversal rides `result.amplified`, so callers can't
        mismatch the flag the producing schedule ran with."""
        from koordinator_tpu.snapshot.delta import forget_pods

        return self.update(lambda s: forget_pods(s, pods, result, mask))
