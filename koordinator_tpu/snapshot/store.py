"""Versioned snapshot store with double-buffered device upload.

The reference keeps informer caches fresh via watch streams and takes an
immutable NodeInfo snapshot per scheduling cycle. Here the host builds a new
columnar snapshot and uploads it asynchronously while the previous version
is still being consumed by in-flight kernels — classic double buffering to
hide HBM transfer latency behind compute (SURVEY.md 2.9) — and between
rebuilds the store stays fresh with O(K) device-side deltas: `ingest`
scatters per-node metric updates, `forget` un-assumes failed binds
(snapshot/delta.py; scheduler_adapter.go assume/forget).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from koordinator_tpu.snapshot.schema import ClusterSnapshot


class SnapshotStore:
    """Holds the current device-resident ClusterSnapshot.

    - `publish(snapshot)` uploads a new version (host numpy pytree) without
      blocking readers; upload overlaps the previous version's compute because
      `jax.device_put` is async.
    - `current()` returns the freshest fully-uploaded version.
    - Optional `sharding` places the node axis across a mesh (parallel/mesh.py).
    """

    def __init__(self, sharding: Optional[Any] = None):
        self._sharding = sharding
        self._lock = threading.Lock()
        self._current: Optional[ClusterSnapshot] = None
        self._version = 0
        # delta replay guard: highest source_version applied since the
        # last full publish (a publish opens a new delta epoch)
        self._applied_delta_version = 0
        self._last_delta_rejection = None
        self.delta_rejections = 0

    @property
    def version(self) -> int:
        return self._version

    @property
    def applied_delta_version(self) -> int:
        return self._applied_delta_version

    def take_delta_rejection(self):
        """Pop the last ingest's DeltaRejectReason (None if it applied)
        — the typed-reason handoff SchedulerService.ingest surfaces to
        the scheduler_delta_rejected metric."""
        with self._lock:
            reason = self._last_delta_rejection
            self._last_delta_rejection = None
            return reason

    def publish(self, snapshot: ClusterSnapshot) -> ClusterSnapshot:
        """Upload a host-built snapshot; returns the device-resident
        pytree. `sharding` may be a single sharding or a pytree of
        shardings matching the snapshot (parallel.snapshot_sharding's
        node-axis layout) — device_put handles either as a prefix."""
        if self._sharding is not None:
            on_device = jax.device_put(snapshot, self._sharding)
        else:
            on_device = jax.device_put(snapshot)
        with self._lock:
            self._version += 1
            self._current = on_device
            # a full publish is a new delta epoch: a restarted producer
            # restarts its sequence at 1 and must not be rejected
            # against a previous epoch's high-water mark
            self._applied_delta_version = 0
            self._last_delta_rejection = None
        return on_device

    def current(self) -> ClusterSnapshot:
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            return self._current

    def update(self, fn: Callable[[ClusterSnapshot], ClusterSnapshot]) -> ClusterSnapshot:
        """Apply a device-side functional update (e.g. post-commit usage
        scatter) and publish the result as the next version."""
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            self._current = fn(self._current)
            self._version += 1
            return self._current

    def ingest(self, delta) -> ClusterSnapshot:
        """Apply a NodeMetricDelta or NodeTopologyDelta device-side
        (snapshot/delta.py): an O(K) upload + scatter instead of an O(N)
        rebuild — the informer event-handler path of the reference, on
        columns. Topology deltas patch node identity (add/remove/update
        rows) within the padded capacity; metric deltas refresh the
        NodeMetric-derived columns.

        Versioned deltas (`source_version` set) are guarded against
        out-of-order / duplicate replay: a version <= the last applied
        one no-ops IDEMPOTENTLY — the snapshot and store version are
        untouched — and the typed reason is held for
        `take_delta_rejection`. Re-applying a stale delta would scatter
        old rows over fresher ones (last-writer-wins per node row), the
        exact mis-apply this guard exists for. Unversioned deltas
        always apply (legacy producers, the sidecar wire)."""
        from koordinator_tpu.snapshot.delta import (
            DeltaRejectReason,
            NodeTopologyDelta,
            apply_metric_delta,
            apply_topology_delta,
            delta_version,
        )

        ver = delta_version(delta)
        if isinstance(delta, NodeTopologyDelta):
            apply = apply_topology_delta
        else:
            apply = apply_metric_delta
        with self._lock:
            if self._current is None:
                raise RuntimeError("no snapshot published yet")
            if ver is not None:
                if ver <= self._applied_delta_version:
                    self._last_delta_rejection = (
                        DeltaRejectReason.DUPLICATE_VERSION
                        if ver == self._applied_delta_version
                        else DeltaRejectReason.STALE_VERSION)
                    self.delta_rejections += 1
                    return self._current
                self._applied_delta_version = ver
            self._last_delta_rejection = None
            self._current = apply(self._current, delta)
            self._version += 1
            return self._current

    def forget(self, pods, result, mask) -> ClusterSnapshot:
        """Un-assume failed binds (scheduler_adapter.go Forget): returns
        the masked pods' charges to the snapshot device-side. The
        amplified-CPU reversal rides `result.amplified`, so callers can't
        mismatch the flag the producing schedule ran with."""
        from koordinator_tpu.snapshot.delta import forget_pods

        return self.update(lambda s: forget_pods(s, pods, result, mask))
