"""bf16 columnar packing for the device-resident snapshot.

The snapshot's float columns split into two families:

  EXACT surfaces — the fit/commit gates compare them with exact
    semantics mirrored from the Go int64 math: NodeState
    allocatable/requested, numa_cap/numa_free, PodBatch requests, the
    quota min/max/used/demand/runtime tree, reservation/device free
    capacity. These stay f32: halving their mantissa would move
    feasibility boundaries.
  SCORE/METRIC surfaces — estimator outputs and usage telemetry the
    scoring paths consume (NodeMetric usage columns, the aggregated
    percentiles, the assigned-pod estimator accumulators, the per-pod
    estimated usage). The estimator itself is a heuristic with >>1%
    model error; carrying these at bf16 (8-bit exponent, 8-bit
    significand) costs well under that while halving the bytes those
    columns occupy on device and on the host->device path.

`pack_*` downcasts exactly the PACKABLE columns to bf16; `unpack_*`
upcasts them back to f32 so every kernel still sees its contracted
dtype (the values are then bf16-rounded f32). Integer/bool columns
(ids, validity, groups) are never touched — placements ride integer
contract surfaces, and the tests pin them bit-identical against the
f32 oracle.

Pad soundness: a packable column's declared `~pad:` fills must survive
the round-trip bit-exactly, or the koordpad annihilator reasoning
(masked reductions meeting exact 0/1/-1/inf fills) breaks under
packing. `validate_packable()` proves that against STRUCT_SPECS at
first use, and `tools/padcheck.py --packed` re-runs the whole Tier-B
differential gate with packed inputs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Tuple

import numpy as np

from koordinator_tpu.snapshot import schema

# struct name -> the columns packing may downcast. Membership is a
# CONTRACT: every entry must be an f32 field whose pad fills are
# bf16-exact (validate_packable), and must feed scoring — never an
# exact fit/commit gate.
PACKABLE: Dict[str, Tuple[str, ...]] = {
    "NodeState": (
        "usage",
        "prod_usage",
        "agg_usage",
        "assigned_estimated",
        "assigned_correction",
        "prod_assigned_estimated",
        "prod_assigned_correction",
    ),
    "PodBatch": (
        "estimated",
    ),
}

# bf16 rounding is 2^-8 relative per element; scoring sums a handful
# of rounded terms, so the documented equivalence tolerance for packed
# float outputs is a few ulps on top (docs/DESIGN.md "bf16 tolerance
# policy"). Integer/bool outputs get NO tolerance: bit-identical.
PACK_RTOL = 0.02
PACK_ATOL = 0.02

_PAD_TOKEN = re.compile(r"~pad:([a-z0-9-]+)")

_validated = False


def _bf16():
    import jax.numpy as jnp
    return jnp.bfloat16


def validate_packable() -> None:
    """Prove the PACKABLE table against the registered struct specs:
    every column exists, is f32 (optionally-absent allowed), and every
    pad predicate it declares has a bf16-exact fill. Raises ValueError
    on any violation — packing an unproven column is a contract bug,
    not a runtime condition."""
    global _validated
    if _validated:
        return
    bf16 = np.dtype(_bf16().dtype) if hasattr(_bf16(), "dtype") \
        else _bf16()
    errors = []
    for struct, fields in PACKABLE.items():
        specs = schema.STRUCT_SPECS.get(struct)
        if specs is None:
            errors.append(f"{struct}: struct not registered")
            continue
        for field in fields:
            raw = specs.get(field)
            if raw is None:
                errors.append(f"{struct}.{field}: no spec")
                continue
            if not isinstance(raw, str):
                errors.append(f"{struct}.{field}: tuple spec "
                              f"not packable")
                continue
            if not raw.lstrip("?").startswith("f32["):
                errors.append(f"{struct}.{field}: dtype is not f32 "
                              f"({raw!r})")
                continue
            for pred in _PAD_TOKEN.findall(raw):
                fill = schema.PAD_FILL_VALUES.get(pred)
                if fill is None:
                    continue  # invalid/any: no fill promised
                rt = np.asarray(fill, np.float32).astype(bf16) \
                    .astype(np.float32)
                if not (rt == np.float32(fill) or
                        (np.isinf(rt) and np.isinf(np.float32(fill)))):
                    errors.append(
                        f"{struct}.{field}: pad fill {fill!r} "
                        f"(~pad:{pred}) is not bf16-exact")
    if errors:
        raise ValueError("packing contract violated:\n  " +
                         "\n  ".join(errors))
    _validated = True


def _convert(value: Any, struct: str, dtype) -> Any:
    """One struct instance with its PACKABLE columns cast to dtype
    (None optionals pass through)."""
    import jax.numpy as jnp
    validate_packable()
    updates = {}
    for field in PACKABLE[struct]:
        col = getattr(value, field)
        if col is None:
            continue
        updates[field] = jnp.asarray(col).astype(dtype)
    return value.replace(**updates) if updates else value


def pack_nodes(nodes) -> Any:
    return _convert(nodes, "NodeState", _bf16())


def unpack_nodes(nodes) -> Any:
    import jax.numpy as jnp
    return _convert(nodes, "NodeState", jnp.float32)


def pack_pods(pods) -> Any:
    return _convert(pods, "PodBatch", _bf16())


def unpack_pods(pods) -> Any:
    import jax.numpy as jnp
    return _convert(pods, "PodBatch", jnp.float32)


def pack_snapshot(snap):
    """ClusterSnapshot with its NodeState score/metric columns stored
    bf16. Quota/reservation/device capacity surfaces are exact-gate
    inputs and stay f32."""
    return snap.replace(nodes=pack_nodes(snap.nodes))


def unpack_snapshot(snap):
    return snap.replace(nodes=unpack_nodes(snap.nodes))


def roundtrip_snapshot(snap):
    """The values a packed snapshot presents to the kernels: f32
    columns carrying bf16-rounded content. Tests and padcheck --packed
    run the scheduler on this against the unpacked oracle."""
    return unpack_snapshot(pack_snapshot(snap))


def roundtrip_pods(pods):
    return unpack_pods(pack_pods(pods))


def roundtrip_tree(tree):
    """Apply the pack/unpack round-trip to every NodeState/PodBatch
    instance inside an arbitrary pytree (ClusterSnapshot included),
    leaving everything else untouched."""
    import jax

    classes = tuple(schema.STRUCT_CLASSES[name] for name in PACKABLE
                    if name in schema.STRUCT_CLASSES)

    def visit(value):
        if isinstance(value, schema.STRUCT_CLASSES.get("NodeState", ())):
            return unpack_nodes(pack_nodes(value))
        if isinstance(value, schema.STRUCT_CLASSES.get("PodBatch", ())):
            return unpack_pods(pack_pods(value))
        return value

    return jax.tree_util.tree_map(
        visit, tree, is_leaf=lambda v: isinstance(v, classes))


def packed_savings(snap, pods=None) -> dict:
    """Bytes the packed layout saves: each packable f32 column drops
    half its payload. Reported by the bench stamp so the win is
    visible next to the timing it buys."""
    saved = 0
    total = 0
    import jax

    for leaf in jax.tree_util.tree_leaves((snap,) if pods is None
                                          else (snap, pods)):
        total += getattr(leaf, "nbytes", 0)
    for struct, owner in (("NodeState", getattr(snap, "nodes", snap)),
                          ("PodBatch", pods)):
        if owner is None:
            continue
        for field in PACKABLE[struct]:
            col = getattr(owner, field, None)
            if col is not None and np.dtype(col.dtype) == np.float32:
                saved += col.nbytes // 2
    return {"bytes_total": int(total), "bytes_saved": int(saved)}
