"""ClusterSnapshot: the device-resident columnar cluster state.

Design (TPU-first, SURVEY.md 2.9):
- Every per-node / per-pod / per-quota map in the reference becomes a fixed-
  shape array column; XLA needs static shapes, so capacities (N nodes, P pods,
  Q quotas, G gangs, Z NUMA zones, V reservations) are padded to the next
  bucket and masked with validity columns.
- All "informer caches" the scheduler hot loop reads (NodeInfo requested/
  allocatable, NodeMetric usage + percentiles, quota tree, gang state,
  reservation state, NUMA free) are materialized here, so one jitted program
  can filter+score+commit a pod batch with zero host round-trips.
- float32 everywhere on the resource axis (canonical units: millicores / MiB)
  — exact-equality semantics of the Go int64 math are preserved by comparing
  with a tolerance chosen so the golden tests match bit-for-bit at realistic
  magnitudes.

Reference parity: NodeInfo snapshot + SLO/NodeMetric/NodeResourceTopology /
quota/gang/reservation caches (SURVEY.md 1, 2.1).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Optional, Tuple, Union

import flax.struct
import jax.numpy as jnp

from koordinator_tpu.api.extension import NUM_RESOURCES

# Aggregation rows in NodeState.agg_usage, in order.
AGG_TYPES = ("avg", "p50", "p90", "p95", "p99")
NUM_AGG = len(AGG_TYPES)

# Static max depth of the elastic-quota tree (root at depth 0).
MAX_QUOTA_DEPTH = 6

# Device-instance resource dims (DeviceState.gpu_free last axis), mirroring
# the GPU resources of apis/extension/device_share.go:44-46.
DEV_CORE = 0    # gpu-core percent (100 == one full GPU)
DEV_MEM = 1     # gpu-memory MiB
DEV_RATIO = 2   # gpu-memory-ratio percent
NUM_DEV_DIMS = 3

# Aux device pools (DeviceState.aux_free axis 1): percent units per instance
# (an RDMA/FPGA virtual function is allocated from one instance).
AUX_RDMA = 0
AUX_FPGA = 1
NUM_AUX_TYPES = 2

Array = Any  # jnp.ndarray (host numpy allowed pre-upload)


@flax.struct.dataclass
class NodeState:
    """Per-node columns. Shapes: [N, ...] with R = NUM_RESOURCES.

    Mirrors: k8s NodeInfo (allocatable/requested), slo NodeMetric status
    (node_usage, prod_usage, aggregated percentiles, freshness), NUMA zones
    from NodeResourceTopology.
    """

    allocatable: Array      # f32[N, R] node allocatable (estimator-adjusted)
    requested: Array        # f32[N, R] sum of requests of assigned pods
    usage: Array            # f32[N, R] NodeMetric nodeUsage
    prod_usage: Array       # f32[N, R] sum of prod-tier pod usages
    agg_usage: Array        # f32[N, NUM_AGG, R] percentile node usage
    assigned_estimated: Array  # f32[N, R] Σ max(estimator(pod), reported
                               # usage) for recently-assigned pods
                               # (podAssignCache / estimatedAssignedPodUsed,
                               # load_aware.go:260-267, 340-378)
    assigned_correction: Array  # f32[N, R] Σ reported usage of those
                                # estimated pods — subtracted from the node
                                # usage source at score time with the >=
                                # guard (load_aware.go:300-315)
    prod_assigned_estimated: Array   # f32[N, R] prod-only variant
    prod_assigned_correction: Array  # f32[N, R] prod-only variant
    metric_fresh: Array     # bool[N] NodeMetric exists and is not expired
    has_agg: Array          # bool[N] aggregated percentiles available
    schedulable: Array      # bool[N] node exists, not cordoned
    label_group: Array      # i32[N] node-label equivalence class (selector gate)
    taint_group: Array      # i32[N] node-taint equivalence class (the
                            # TaintToleration gate rides [T, TG] matrices
                            # exactly like the selector gate)
    # NUMA (Z zones): cpu/mem capacity and free per zone
    numa_cap: Array         # f32[N, Z, 2] (cpu milli, mem MiB)
    numa_free: Array        # f32[N, Z, 2]
    numa_valid: Array       # bool[N, Z]
    numa_policy: Array      # i32[N] topology-manager policy code
                            # (scheduler/topologymanager.py POLICY_*;
                            # apis/extension numa-topology-policy label)
    cpu_amplification: Array  # f32[N] node CPU amplification ratio (>= 1;
                            # resource-amplification-ratio annotation). The
                            # webhook publishes AMPLIFIED allocatable; a
                            # CPU-bind pod's exclusive cores cost
                            # request x ratio against it
                            # (nodenumaresource filterAmplifiedCPUs)

    @property
    def num_nodes(self) -> int:
        return self.allocatable.shape[0]


@flax.struct.dataclass
class PodBatch:
    """The pending-pod batch being scheduled. Shapes: [P, ...].

    `requests` are already translated to the priority tier's extended
    resources (api.extension.translate_resource_by_priority);
    `estimated` is the LoadAware estimator output
    (estimator/default_estimator.go:62-110).
    """

    requests: Array         # f32[P, R]
    estimated: Array        # f32[P, R]
    qos: Array              # i8[P] QoSClass
    priority_class: Array   # i8[P] PriorityClass
    priority: Array         # i32[P] numeric priority (bands, tie-break)
    gang_id: Array          # i32[P] index into GangState, -1 = none
    quota_id: Array         # i32[P] index into QuotaState, -1 = none
    selector_id: Array      # i32[P] row into selector_match, -1 = match all
    selector_match: Array   # bool[S, L] selector s matches node-label-group l
                            # (distinct pod selectors x distinct node label
                            # sets — the nodeSelector gate without a P x N
                            # host-side matrix)
    reservation_owner: Array  # i32[P] owner-match group for reservations, -1
    gpu_ratio: Array        # f32[P] explicit gpu-memory-ratio request (0 =
                            # unspecified; requests[GPU_MEMORY] > 0 wins and
                            # the ratio is derived per node from the node's
                            # GPU memory, devicehandler_gpu.go:68-90)
    numa_single: Array      # bool[P] requires single-NUMA-node placement
    daemonset: Array        # bool[P] DaemonSet pods bypass LoadAware filter
                            # (load_aware.go isDaemonSetPod)
    toleration_id: Array    # i32[P] row into the toleration matrices
                            # (row 0 = the empty toleration set)
    tol_forbid: Array       # bool[T, TG] toleration set t leaves an
                            # untolerated NoSchedule/NoExecute taint on
                            # node-taint-group g (TaintToleration filter)
    tol_prefer: Array       # f32[T, TG] count of untolerated
                            # PreferNoSchedule taints (score penalty,
                            # upstream tainttoleration scoring)
    # PodTopologySpread (upstream hard constraints), batched: pods with
    # an identical (namespace, key, skew, selector) constraint share a
    # spread group; [1, 1]-shaped matrices mean no spread modeling and
    # the gate compiles out. Gating runs at ROUND granularity — exact at
    # chunk size 1 like every other commit gate. A pod carrying SEVERAL
    # constraints (zone + hostname is the upstream default profile) is
    # gated by each via the carrier MATRIX, the same shape as anti.
    spread_id: Array        # i32[P] FIRST carried group (diagnostics;
                            # gating rides spread_carrier), -1 = none
    spread_carrier: Array   # bool[P, Sg] pod carries group's constraint
    spread_member: Array    # bool[P, Sg] pod matches group's selector
                            # (charges the domain count when placed, even
                            # without carrying the constraint itself)
    spread_max_skew: Array  # f32[Sg]
    spread_domain: Array    # i32[Sg, N] node's domain for the group's
                            # topology key, -1 = node lacks the label
                            # (hard constraints reject such nodes)
    spread_count0: Array    # f32[Sg, D] matching running pods per domain
    spread_dvalid: Array    # bool[Sg, D] domain exists in the cluster
    # inter-pod affinity/anti-affinity (upstream required terms), the
    # same (group, domain) machinery: anti groups admit a domain only at
    # count 0 (nodes LACKING the topology key pass — no pair can exist);
    # affinity groups require count > 0, with a bootstrap when nothing
    # matches anywhere and the pod matches its own selector. The
    # per-(pod, group) member matrices mark which BATCH pods charge a
    # group's domain counts when placed — membership is by selector
    # match, so a matching pod that doesn't carry the term still counts
    # (upstream counts all matching pods, not just constrained ones).
    # Anti-affinity is enforced in BOTH directions with separate count
    # surfaces per group (one per distinct required term):
    # (a) a pod CARRYING a term avoids domains holding selector-
    #     matching pods (the anti_carrier MATRIX gates against
    #     anti_count0 + placed anti_member charges — a pod carrying
    #     SEVERAL terms is gated by each);
    # (b) a pod MATCHING the selector avoids domains holding term
    #     CARRIERS (anti_member gates against anti_carrier_count0 +
    #     placed anti_carrier charges) — satisfyExistingPodsAntiAffinity
    #     generalized to same-batch carriers.
    anti_id: Array          # i32[P] FIRST carried group (diagnostics;
                            # gating rides anti_carrier), -1 = none
    anti_member: Array      # bool[P, Ag] pod matches group's selector
    anti_carrier: Array     # bool[P, Ag] pod carries group's term
    anti_domain: Array      # i32[Ag, N]
    anti_count0: Array      # f32[Ag, D] matching running/assumed pods
    anti_carrier_count0: Array  # f32[Ag, D] carrier running/assumed pods
    # affinity: a pod carrying several required terms must satisfy each
    # (carrier matrix, like anti/spread)
    aff_id: Array           # i32[P] FIRST carried group (diagnostics;
                            # gating rides aff_carrier), -1 = none
    aff_carrier: Array      # bool[P, Fg] pod carries group's term
    aff_member: Array       # bool[P, Fg]
    aff_domain: Array       # i32[Fg, N]
    aff_count0: Array       # f32[Fg, D]
    valid: Array            # bool[P]
    # STATIC gate switches (aux data, not arrays): whether the batch
    # models each constraint family. Shape-based sentinels are ambiguous
    # — a legitimate 1-group/1-domain config collides with the [1, 1]
    # degenerate — so the builder sets these explicitly and the
    # scheduler compiles each gate in/out on them.
    has_taints: bool = flax.struct.field(pytree_node=False, default=False)
    has_spread: bool = flax.struct.field(pytree_node=False, default=False)
    has_anti: bool = flax.struct.field(pytree_node=False, default=False)
    has_aff: bool = flax.struct.field(pytree_node=False, default=False)

    @property
    def num_pods(self) -> int:
        return self.requests.shape[0]


# The [P]-leading PodBatch columns — the fields a per-pod gather/reorder
# (chunk slicing, prefix packing, straggler-tail compaction) must
# permute together; batch-global matrices (selector_match, the
# (group x domain) tables, count0 surfaces) stay put. THE one list:
# synthetic.stack_pod_chunks/slice_batch, the bench sweep, and the
# device-resident tail (scheduler/core.tail_pass) all consume it.
PER_POD_FIELDS = ("requests", "estimated", "qos", "priority_class",
                  "priority", "gang_id", "quota_id", "selector_id",
                  "reservation_owner", "gpu_ratio", "numa_single",
                  "daemonset", "toleration_id", "spread_id",
                  "spread_carrier", "spread_member", "anti_id",
                  "anti_member", "anti_carrier", "aff_id", "aff_carrier",
                  "aff_member", "valid")


@flax.struct.dataclass
class QuotaState:
    """Hierarchical elastic-quota tree, flattened. Shapes: [Q, ...].

    `ancestors[q, a]` is True when quota `a` is `q` or an ancestor of `q` —
    the device-side equivalent of walking parentInfos
    (elasticquota/plugin.go:211-257). Runtime is recomputed by the
    water-filling kernel (ops/waterfill.py).
    """

    min: Array              # f32[Q, R] guaranteed
    max: Array              # f32[Q, R] cap (inf if unlimited)
    shared_weight: Array    # f32[Q, R] fair-share weight (default = max)
    parent: Array           # i32[Q] parent index, -1 = root's parent
    ancestors: Array        # bool[Q, Q]
    depth_ancestor: Array   # i32[Q, D] ancestor at depth d (self included),
                            # -1 past the leaf — lets the commit kernel do an
                            # exact per-level prefix gate without a Q x Q
                            # matmul per pod (D = MAX_QUOTA_DEPTH)
    used: Array             # f32[Q, R] admitted usage
    demand: Array           # f32[Q, R] DIRECT pod demand charged to the
                            # pod's own quota only; ops.waterfill propagates
                            # it bottom-up with the per-level min/max clamp
                            # into limitedRequest (quota_info.go
                            # getLimitRequestNoLock + group_quota_manager.go
                            # recursiveUpdateGroupTreeWithDeltaRequest)
    allow_lent: Array       # bool[Q] allowLentResource: lend unused min
    runtime: Array          # f32[Q, R] water-filled entitlement
    valid: Array            # bool[Q]


@flax.struct.dataclass
class GangState:
    """Coscheduling gang/PodGroup state. Shapes: [G, ...].

    Mirrors core/gang.go:43-83 state machine inputs: minMember quorum and
    the count already assumed/bound.
    """

    min_member: Array       # i32[G]
    member_count: Array     # i32[G] total members seen (quorum check)
    assumed: Array          # i32[G] members already assumed/bound
    strict: Array           # bool[G] strict mode
    satisfied: Array        # bool[G] match-policy satisfied latch: members
    #   pass the gang gates individually and are exempt from all-or-nothing
    #   rollback (core.go:236,286 — a once-satisfied gang short-circuits
    #   PreFilter and is never group-rejected in PostFilter)
    valid: Array            # bool[G]


@flax.struct.dataclass
class DeviceState:
    """Per-node device instances (DeviceShare nodeDeviceCache, SURVEY.md 2.1
    plugins/deviceshare: Device CRs mirrored as device columns).

    GPU pool: I instances per node, each with (core %, memory MiB, memory-
    ratio %) free. A node carries one GPU model, so per-instance totals are a
    single [N, 3] row (devicehandler_gpu.go:82 "a node can only contain one
    type of GPU"). Aux pools (RDMA/FPGA) are percent-unit instances; a
    request is served from a single instance (default device handler
    semantics: desiredCount 1).
    """

    gpu_total: Array        # f32[N, 3] per-INSTANCE totals (core=100 when
                            # present, memory MiB, ratio=100)
    gpu_free: Array         # f32[N, I, 3]
    gpu_valid: Array        # bool[N, I] instance exists and is healthy
    gpu_numa: Array         # i32[N, I] NUMA node of the instance, -1 unknown
    gpu_pcie: Array         # i32[N, I] PCIe root id, -1 unknown (host bind
                            # uses it for joint-allocate minor preference)
    aux_free: Array         # f32[N, A, J] percent free per aux instance
    aux_valid: Array        # bool[N, A, J]

    @property
    def num_instances(self) -> int:
        return self.gpu_free.shape[1]


@flax.struct.dataclass
class ReservationState:
    """Available reservations as device columns. Shapes: [V, ...].

    A reservation is reserved capacity *already counted* in node `requested`;
    a matching pod first consumes reservation free capacity (restore
    semantics, reservation/transformer.go:240-291). Reservations holding
    GPU instances or a NUMA cpuset carry those as per-slot pools the
    scheduler hands to consumers (the deviceshare / nodenumaresource
    ReservationRestorePlugin state): instance columns are indexed by the
    UNDERLYING NODE's minors/zones, so a consumer's grant is directly a
    node-level allocation.
    """

    node: Array             # i32[V] node index the reservation landed on
    free: Array             # f32[V, R] remaining reserved capacity
    owner_group: Array      # i32[V] owner-match group id
    allocate_once: Array    # bool[V]
    valid: Array            # bool[V]
    # reserved device instances (remaining per-instance capacity; zero
    # rows for minors the reservation does not hold)
    gpu_free: Array         # f32[V, I, NUM_DEV_DIMS]
    gpu_valid: Array        # bool[V, I] reserved minors
    # reserved NUMA zone capacity remaining (cpu milli, mem MiB)
    numa_free: Array        # f32[V, Z, 2]
    numa_valid: Array       # bool[V, Z] reserved zones


@flax.struct.dataclass
class ClusterSnapshot:
    """The complete device-resident cluster state (one version)."""

    nodes: NodeState
    quotas: QuotaState
    gangs: GangState
    reservations: ReservationState
    devices: DeviceState
    version: Array          # i32[] monotonically increasing

    @property
    def num_nodes(self) -> int:
        return self.nodes.num_nodes


def zeros_devices(num_nodes: int, num_gpu_inst: int = 0,
                  num_aux_inst: int = 0) -> DeviceState:
    """An all-empty device pool with the given static instance capacities."""
    n, i, j = num_nodes, num_gpu_inst, num_aux_inst
    f32 = jnp.float32
    return DeviceState(
        gpu_total=jnp.zeros((n, NUM_DEV_DIMS), f32),
        gpu_free=jnp.zeros((n, i, NUM_DEV_DIMS), f32),
        gpu_valid=jnp.zeros((n, i), bool),
        gpu_numa=jnp.full((n, i), -1, jnp.int32),
        gpu_pcie=jnp.full((n, i), -1, jnp.int32),
        aux_free=jnp.zeros((n, NUM_AUX_TYPES, j), f32),
        aux_valid=jnp.zeros((n, NUM_AUX_TYPES, j), bool),
    )


def zeros_snapshot(num_nodes: int, num_quotas: int = 1, num_gangs: int = 1,
                   num_reservations: int = 1, num_zones: int = 4,
                   num_gpu_inst: int = 0,
                   num_aux_inst: int = 0) -> ClusterSnapshot:
    """An all-empty snapshot with the given static capacities."""
    n, q, g, v, z, r = (num_nodes, num_quotas, num_gangs, num_reservations,
                        num_zones, NUM_RESOURCES)
    f32 = jnp.float32
    nodes = NodeState(
        allocatable=jnp.zeros((n, r), f32),
        requested=jnp.zeros((n, r), f32),
        usage=jnp.zeros((n, r), f32),
        prod_usage=jnp.zeros((n, r), f32),
        agg_usage=jnp.zeros((n, NUM_AGG, r), f32),
        assigned_estimated=jnp.zeros((n, r), f32),
        assigned_correction=jnp.zeros((n, r), f32),
        prod_assigned_estimated=jnp.zeros((n, r), f32),
        prod_assigned_correction=jnp.zeros((n, r), f32),
        metric_fresh=jnp.zeros((n,), bool),
        has_agg=jnp.zeros((n,), bool),
        schedulable=jnp.zeros((n,), bool),
        label_group=jnp.zeros((n,), jnp.int32),
        numa_cap=jnp.zeros((n, z, 2), f32),
        numa_free=jnp.zeros((n, z, 2), f32),
        numa_valid=jnp.zeros((n, z), bool),
        numa_policy=jnp.zeros((n,), jnp.int32),
        cpu_amplification=jnp.ones((n,), f32),
        taint_group=jnp.zeros((n,), jnp.int32),
    )
    quotas = QuotaState(
        min=jnp.zeros((q, r), f32),
        max=jnp.full((q, r), jnp.inf, f32),
        shared_weight=jnp.zeros((q, r), f32),
        parent=jnp.full((q,), -1, jnp.int32),
        ancestors=jnp.zeros((q, q), bool),
        depth_ancestor=jnp.full((q, MAX_QUOTA_DEPTH), -1, jnp.int32),
        used=jnp.zeros((q, r), f32),
        demand=jnp.zeros((q, r), f32),
        allow_lent=jnp.ones((q,), bool),
        runtime=jnp.full((q, r), jnp.inf, f32),
        valid=jnp.zeros((q,), bool),
    )
    gangs = GangState(
        min_member=jnp.ones((g,), jnp.int32),
        member_count=jnp.zeros((g,), jnp.int32),
        assumed=jnp.zeros((g,), jnp.int32),
        strict=jnp.ones((g,), bool),
        satisfied=jnp.zeros((g,), bool),
        valid=jnp.zeros((g,), bool),
    )
    reservations = ReservationState(
        node=jnp.full((v,), -1, jnp.int32),
        free=jnp.zeros((v, r), f32),
        owner_group=jnp.full((v,), -1, jnp.int32),
        allocate_once=jnp.ones((v,), bool),
        valid=jnp.zeros((v,), bool),
        gpu_free=jnp.zeros((v, num_gpu_inst, NUM_DEV_DIMS), f32),
        gpu_valid=jnp.zeros((v, num_gpu_inst), bool),
        numa_free=jnp.zeros((v, z, 2), f32),
        numa_valid=jnp.zeros((v, z), bool),
    )
    return ClusterSnapshot(nodes=nodes, quotas=quotas, gangs=gangs,
                           reservations=reservations,
                           devices=zeros_devices(n, num_gpu_inst,
                                                 num_aux_inst),
                           version=jnp.zeros((), jnp.int32))


# --- kernel shape contracts ------------------------------------------------
#
# Every jitted entry point (and the kernel helpers it composes) declares a
# machine-checked contract over the named-dimension vocabulary below:
# which dims each argument/output carries, its dtype, and the pad
# semantics callers rely on. Two independent checkers consume the
# registry:
#   Tier A (static, stdlib-only): koordlint's `shape-contract` pass reads
#     the decorator calls straight from the AST (tools/lint/shapes) and
#     abstractly interprets kernel bodies against the declared dims.
#   Tier B (device-free dynamic): tools/shapecheck.py imports this
#     registry and drives jax.eval_shape over every contract with
#     symbolic-sized ShapeDtypeStructs — no device, no compile.
# The decorator itself is a pure registration: zero tracing or runtime
# cost, and every spec is a literal string so the AST tier never has to
# execute anything.
#
# Spec grammar (tools/lint/shapes/spec.py is the single parser):
#   "f32[P,N]"   leaf array: dtype in {f32, i32, i8, u32, bool},
#                dims = named symbols, fixed symbols, or int literals
#   "f32[]"      scalar array
#   "?f32[P,N]"  optional: the value may be None (e.g. compiled-out gates)
#   "PodBatch"   a registered struct (register_struct below)
#   "N"          a bare dim symbol marks a symbolic-int PROPERTY of a
#                struct (documentation for the AST tier; never built)
#   "f32[N~pad:zero,R]"  a PADDED dim declares its pad predicate (the
#                koordpad tier): what the pad region along that dim
#                contains. Three checkers consume the predicates:
#                koordlint's pad-soundness pass (PS001-PS005, static
#                mask-provenance dataflow), tools/padcheck.py (concrete
#                differential runs under two paddings), and
#                parallel/mesh.py's pad fills. PAD_VOCAB below names
#                the predicates; PADDED_DIMS names the dims that must
#                carry one.

# the named-dimension vocabulary — THE shared meaning of every symbol;
# tools/lint/shapes/spec.py carries the same table for the stdlib-only
# tier and tests/test_shape_contract.py pins the two in sync
DIM_VOCAB = {
    "P": "pending pods in the batch",
    "N": "node columns (padded capacity)",
    "I": "GPU instances per node",
    "Z": "NUMA zones per node",
    "G": "gangs (PodGroups)",
    "Q": "elastic-quota tree nodes",
    "V": "reservation slots",
    "R": "resource dims (NUM_RESOURCES; padded like any capacity)",
    "S": "distinct pod node-selectors",
    "L": "node label-equivalence groups",
    "T": "distinct pod toleration sets",
    "TG": "node taint-equivalence groups",
    "SG": "pod-topology-spread groups",
    "AG": "inter-pod anti-affinity groups",
    "FG": "inter-pod affinity groups",
    "DM": "topology domains per constraint group",
    "J": "aux (RDMA/FPGA) VF instances per pool",
    "K": "delta rows per ingest tick",
    "KC": "gathered per-shard top-k candidates (k x node shards)",
    "TC": "tail retry-chunk width",
    "RD": "descheduler threshold resource dims",
    "NS": "descheduler namespace rows (padded)",
}

# dims pinned to module constants rather than free sizes
FIXED_DIMS = {
    "AGG": NUM_AGG,          # aggregation percentile rows
    "DEV": NUM_DEV_DIMS,     # GPU instance resource dims (core/mem/ratio)
    "AX": NUM_AUX_TYPES,     # aux device pools (rdma, fpga)
    "QD": MAX_QUOTA_DEPTH,   # quota-tree depth
}

# the pad-predicate vocabulary (the koordpad tier) — what a `~pad:` token
# on a padded dim promises about the pad region along that dim;
# tools/lint/shapes/spec.py carries the same table for the stdlib-only
# tier and tests/test_pad_soundness.py pins the two in sync
PAD_VOCAB = {
    "zero": "pad entries are 0 (False for bool)",
    "one": "pad entries are 1 (True for bool)",
    "false": "pad entries are False (bool columns only)",
    "-1": "pad entries carry the -1 'none' sentinel",
    "inf": "pad entries are +inf (never gate; f32 only)",
    "unschedulable": "zero-filled node rows additionally killed by the "
                     "schedulable=False guard (pad_nodes_to_mesh rows)",
    "invalid": "content unspecified; masked by the carrying struct's "
               "validity column (valid/gpu_valid/numa_valid/...)",
    "any": "content unspecified; every consumer must guard it "
           "explicitly (no inertness is asserted)",
}

# dims that take padded capacity and therefore MUST declare a pad
# predicate wherever they appear in a struct field or contract spec
# (the PS004 totality check). Deliberately NOT here:
#   R          fixed resource axis — NUM_RESOURCES is exact, never padded
#   S/L/T/TG/  exact equivalence-class tables sized by distinct values,
#   SG/AG/FG     not bucketed capacities
#   TC         static tail retry-chunk width (a tuning constant; varying
#                it changes tail-loop iteration stats, not padding)
#   KC/RD      derived widths (k x shards, threshold rows) — exact
PADDED_DIMS = frozenset({
    "P", "N", "Q", "G", "V", "Z", "I", "J", "DM", "K", "NS",
})

# pad predicate -> the concrete fill value tools/padcheck.py and the
# mesh padder materialize for it (None: no single canonical fill — the
# predicate is a masking promise, not a value)
PAD_FILL_VALUES = {
    "zero": 0,
    "one": 1,
    "false": 0,
    "-1": -1,
    "inf": float("inf"),
    "unschedulable": 0,
    "invalid": None,
    "any": None,
}

FieldSpec = Union[str, Tuple[str, ...]]


class ShapeContract:
    """One kernel's declared tensor contract (a plain record; the
    checkers interpret it — nothing here touches jax)."""

    __slots__ = ("name", "module", "fn", "args", "returns", "static",
                 "callables", "pad")

    def __init__(self, name: str, module: str, fn: Callable,
                 args: Dict[str, FieldSpec], returns: FieldSpec,
                 static: Dict[str, Any], callables: Dict[str, str],
                 pad: str):
        self.name = name
        self.module = module
        self.fn = fn
        self.args = args
        self.returns = returns
        self.static = static
        self.callables = callables
        self.pad = pad

    @property
    def key(self) -> str:
        return f"{self.module}.{self.name}"


# key: "module.function" -> contract (import the defining modules to
# populate; tools/shapecheck.py owns the canonical import list)
SHAPE_CONTRACTS: Dict[str, ShapeContract] = {}
# struct name -> {field: spec}; bare-symbol entries are symbolic-int
# properties (num_nodes = "N"), never constructor fields
STRUCT_SPECS: Dict[str, Dict[str, FieldSpec]] = {}
# struct name -> class, for Tier B instance construction
STRUCT_CLASSES: Dict[str, type] = {}


def register_struct(cls: type, fields: Dict[str, FieldSpec]) -> type:
    """Declare the per-field shape specs of a pytree struct. Static
    (pytree_node=False) fields are omitted — they keep their defaults
    when Tier B builds abstract instances."""
    name = cls.__name__
    prior = STRUCT_SPECS.get(name)
    if prior is not None and prior != fields:
        raise ValueError(f"struct {name!r} re-registered with a "
                         f"different spec")
    STRUCT_SPECS[name] = dict(fields)
    STRUCT_CLASSES[name] = cls
    return cls


def shape_contract(_returns: FieldSpec = None,
                   _static: Optional[Mapping[str, Any]] = None,
                   _callable: Optional[Mapping[str, str]] = None,
                   _pad: str = "",
                   **arg_specs: FieldSpec) -> Callable:
    """Decorator: register the function's kernel shape contract.

    `arg_specs` maps TRACED argument names to specs; static arguments
    the checker must supply go in `_static` (a value that names a dim
    symbol, e.g. "TC", resolves to that dim's assigned size). `_callable`
    maps higher-order arguments to the dotted path of another contracted
    function Tier B substitutes. Apply ABOVE jax.jit so the registered
    callable is the jitted wrapper (eval_shape traces it abstractly).
    """

    def deco(fn: Callable) -> Callable:
        name = getattr(fn, "__name__", None)
        module = getattr(fn, "__module__", None)
        if not name or not module:
            raise ValueError("shape_contract target has no name/module")
        c = ShapeContract(name=name, module=module, fn=fn,
                          args=dict(arg_specs), returns=_returns,
                          static=dict(_static or {}),
                          callables=dict(_callable or {}), pad=_pad)
        if c.key in SHAPE_CONTRACTS:
            raise ValueError(f"duplicate shape contract {c.key}")
        SHAPE_CONTRACTS[c.key] = c
        return fn

    return deco


register_struct(NodeState, {
    "allocatable": "f32[N~pad:unschedulable,R]",
    "requested": "f32[N~pad:unschedulable,R]",
    "usage": "f32[N~pad:unschedulable,R]",
    "prod_usage": "f32[N~pad:unschedulable,R]",
    "agg_usage": "f32[N~pad:unschedulable,AGG,R]",
    "assigned_estimated": "f32[N~pad:unschedulable,R]",
    "assigned_correction": "f32[N~pad:unschedulable,R]",
    "prod_assigned_estimated": "f32[N~pad:unschedulable,R]",
    "prod_assigned_correction": "f32[N~pad:unschedulable,R]",
    "metric_fresh": "bool[N~pad:false]",
    "has_agg": "bool[N~pad:false]",
    "schedulable": "bool[N~pad:false]",
    "label_group": "i32[N~pad:zero]",
    "taint_group": "i32[N~pad:zero]",
    "numa_cap": "f32[N~pad:unschedulable,Z~pad:zero,2]",
    "numa_free": "f32[N~pad:unschedulable,Z~pad:zero,2]",
    "numa_valid": "bool[N~pad:false,Z~pad:false]",
    "numa_policy": "i32[N~pad:zero]",
    "cpu_amplification": "f32[N~pad:one]",
    "num_nodes": "N",
})

register_struct(PodBatch, {
    "requests": "f32[P~pad:zero,R]",
    "estimated": "f32[P~pad:zero,R]",
    "qos": "i8[P~pad:zero]",
    "priority_class": "i8[P~pad:zero]",
    "priority": "i32[P~pad:zero]",
    "gang_id": "i32[P~pad:-1]",
    "quota_id": "i32[P~pad:-1]",
    "selector_id": "i32[P~pad:-1]",
    "selector_match": "bool[S,L]",
    "reservation_owner": "i32[P~pad:-1]",
    "gpu_ratio": "f32[P~pad:zero]",
    "numa_single": "bool[P~pad:false]",
    "daemonset": "bool[P~pad:false]",
    "toleration_id": "i32[P~pad:zero]",
    "tol_forbid": "bool[T,TG]",
    "tol_prefer": "f32[T,TG]",
    "spread_id": "i32[P~pad:-1]",
    "spread_carrier": "bool[P~pad:false,SG]",
    "spread_member": "bool[P~pad:false,SG]",
    "spread_max_skew": "f32[SG]",
    "spread_domain": "i32[SG,N~pad:-1]",
    "spread_count0": "f32[SG,DM~pad:zero]",
    "spread_dvalid": "bool[SG,DM~pad:false]",
    "anti_id": "i32[P~pad:-1]",
    "anti_member": "bool[P~pad:false,AG]",
    "anti_carrier": "bool[P~pad:false,AG]",
    "anti_domain": "i32[AG,N~pad:-1]",
    "anti_count0": "f32[AG,DM~pad:zero]",
    "anti_carrier_count0": "f32[AG,DM~pad:zero]",
    "aff_id": "i32[P~pad:-1]",
    "aff_carrier": "bool[P~pad:false,FG]",
    "aff_member": "bool[P~pad:false,FG]",
    "aff_domain": "i32[FG,N~pad:-1]",
    "aff_count0": "f32[FG,DM~pad:zero]",
    "valid": "bool[P~pad:false]",
    "num_pods": "P",
})

register_struct(QuotaState, {
    "min": "f32[Q~pad:zero,R]",
    "max": "f32[Q~pad:inf,R]",
    "shared_weight": "f32[Q~pad:zero,R]",
    "parent": "i32[Q~pad:-1]",
    "ancestors": "bool[Q~pad:false,Q~pad:false]",
    "depth_ancestor": "i32[Q~pad:-1,QD]",
    "used": "f32[Q~pad:zero,R]",
    "demand": "f32[Q~pad:zero,R]",
    "allow_lent": "bool[Q~pad:one]",
    "runtime": "f32[Q~pad:inf,R]",
    "valid": "bool[Q~pad:false]",
})

register_struct(GangState, {
    "min_member": "i32[G~pad:one]",
    "member_count": "i32[G~pad:zero]",
    "assumed": "i32[G~pad:zero]",
    "strict": "bool[G~pad:one]",
    "satisfied": "bool[G~pad:false]",
    "valid": "bool[G~pad:false]",
})

register_struct(DeviceState, {
    "gpu_total": "f32[N~pad:zero,DEV]",
    "gpu_free": "f32[N~pad:zero,I~pad:zero,DEV]",
    "gpu_valid": "bool[N~pad:false,I~pad:false]",
    "gpu_numa": "i32[N~pad:-1,I~pad:-1]",
    "gpu_pcie": "i32[N~pad:-1,I~pad:-1]",
    "aux_free": "f32[N~pad:zero,AX,J~pad:zero]",
    "aux_valid": "bool[N~pad:false,AX,J~pad:false]",
    "num_instances": "I",
})

register_struct(ReservationState, {
    "node": "i32[V~pad:-1]",
    "free": "f32[V~pad:zero,R]",
    "owner_group": "i32[V~pad:-1]",
    "allocate_once": "bool[V~pad:one]",
    "valid": "bool[V~pad:false]",
    "gpu_free": "f32[V~pad:zero,I~pad:zero,DEV]",
    "gpu_valid": "bool[V~pad:false,I~pad:false]",
    "numa_free": "f32[V~pad:zero,Z~pad:zero,2]",
    "numa_valid": "bool[V~pad:false,Z~pad:false]",
})

register_struct(ClusterSnapshot, {
    "nodes": "NodeState",
    "quotas": "QuotaState",
    "gangs": "GangState",
    "reservations": "ReservationState",
    "devices": "DeviceState",
    "version": "i32[]",
    "num_nodes": "N",
})
