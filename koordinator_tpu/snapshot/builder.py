"""Host-side ingest: typed API objects -> columnar numpy -> ClusterSnapshot.

This is the equivalent of the reference's informer caches + NodeInfo snapshot
construction, plus the host half of the LoadAware plugin's per-cycle state:

- the estimator (estimator/default_estimator.go:62-110) is vectorized here so
  PodBatch.estimated is precomputed once per batch;
- the podAssignCache adjustment (load_aware.go:260-267, 340-378:
  estimatedAssignedPodUsed) is folded into NodeState.assigned_estimated and a
  usage correction, so the device score kernel is pure arithmetic.

Everything is plain numpy; `SnapshotStore` (store.py) owns device upload.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.api.extension import (
    NUM_RESOURCES,
    PriorityClass,
    QoSClass,
    ResourceKind,
    node_cpu_amplification_ratio,
    numa_policy_code,
    translate_resource_by_priority,
)
from koordinator_tpu.api.types import (
    Device,
    ElasticQuota,
    Node,
    NodeMetric,
    Pod,
    PodGroup,
    Reservation,
    ResourceList,
    Taint,
)
from koordinator_tpu.snapshot.schema import (
    AGG_TYPES,
    AUX_FPGA,
    AUX_RDMA,
    ClusterSnapshot,
    DEV_CORE,
    DEV_MEM,
    DeviceState,
    GangState,
    MAX_QUOTA_DEPTH,
    NodeState,
    NUM_AGG,
    NUM_AUX_TYPES,
    NUM_DEV_DIMS,
    PodBatch,
    QuotaState,
    ReservationState,
)

# Defaults mirroring LoadAwareSchedulingArgs defaulting
# (scheduler/apis/config/v1beta2/defaults.go semantics per types.go:30-58).
DEFAULT_RESOURCE_WEIGHTS: Dict[ResourceKind, float] = {
    ResourceKind.CPU: 1.0,
    ResourceKind.MEMORY: 1.0,
}
DEFAULT_USAGE_THRESHOLDS: Dict[ResourceKind, float] = {
    ResourceKind.CPU: 65.0,
    ResourceKind.MEMORY: 95.0,
}
DEFAULT_SCALING_FACTORS: Dict[ResourceKind, float] = {
    ResourceKind.CPU: 85.0,
    ResourceKind.MEMORY: 70.0,
}
DEFAULT_MILLI_CPU_REQUEST = 250.0          # load_aware.go:52
DEFAULT_MEMORY_REQUEST_MIB = 200.0         # load_aware.go:54 (200*1024*1024 B)
DEFAULT_NODE_METRIC_EXPIRATION_S = 180.0   # types.go:38
DEFAULT_REPORT_INTERVAL_S = 60.0           # load_aware.go:56


def resource_vec(rl: ResourceList) -> np.ndarray:
    v = np.zeros((NUM_RESOURCES,), np.float32)
    for k, val in rl.items():
        v[int(k)] = val
    return v


def round_half_away(x):
    """Go math.Round: half away from zero (values here are >= 0).
    np.round's banker's rounding flips filter decisions at exact .5
    boundaries, so it must not be used for reference-parity math."""
    return np.floor(np.asarray(x, np.float64) + 0.5)


def estimate_pod(pod: Pod,
                 scaling_factors: Mapping[ResourceKind, float] = None,
                 weights: Mapping[ResourceKind, float] = None) -> np.ndarray:
    """DefaultEstimator.EstimatePod (estimator/default_estimator.go:57-110).

    For each weighted resource (cpu/memory), read the request of the pod's
    priority tier's translated resource; if limit > request use the limit at
    100%; else scale the request by the factor; zero requests fall back to
    250m / 200MiB; result is capped at the limit. Output is keyed by the
    *native* resource dim (scores compare against native allocatable).
    """
    scaling_factors = scaling_factors or DEFAULT_SCALING_FACTORS
    weights = weights or DEFAULT_RESOURCE_WEIGHTS
    pc = pod.priority_class
    out = np.zeros((NUM_RESOURCES,), np.float32)
    for kind in weights:
        real = translate_resource_by_priority(kind, pc)
        req = float(pod.requests.get(real, 0.0))
        lim = float(pod.limits.get(real, 0.0))
        factor = float(scaling_factors.get(kind, 100.0))
        if lim > req:
            qty, factor = lim, 100.0
        else:
            qty = req
        if qty == 0.0:
            if real in (ResourceKind.CPU, ResourceKind.BATCH_CPU,
                        ResourceKind.MID_CPU):
                out[int(kind)] = DEFAULT_MILLI_CPU_REQUEST
            elif real in (ResourceKind.MEMORY, ResourceKind.BATCH_MEMORY,
                          ResourceKind.MID_MEMORY):
                out[int(kind)] = DEFAULT_MEMORY_REQUEST_MIB
            continue
        est = round_half_away(qty * factor / 100.0)
        if lim > 0:
            est = min(est, lim)
        out[int(kind)] = est
    return out


def gpu_per_instance_host(total_mem: float, pod: Pod) -> Tuple[int, np.ndarray]:
    """Host mirror of the device kernel's per-instance GPU request math
    (deviceshare devicehandler_gpu.go:40-66; scheduler/plugins/deviceshare
    _per_instance): returns (count, per_inst f32[3])."""
    core = float(pod.requests.get(ResourceKind.GPU_CORE, 0.0))
    mem = float(pod.requests.get(ResourceKind.GPU_MEMORY, 0.0))
    ratio = float(pod.gpu_memory_ratio)
    if core <= 0 and mem <= 0 and ratio <= 0:
        return 0, np.zeros((NUM_DEV_DIMS,), np.float32)
    if mem > 0:
        ratio_eff = np.floor(mem / max(total_mem, 1.0) * 100.0)
        mem_eff = mem
    else:
        ratio_eff = ratio
        mem_eff = np.floor(ratio * total_mem / 100.0)
    count = int(ratio_eff // 100) if (ratio_eff > 100
                                      and ratio_eff % 100 == 0) else 1
    per_inst = np.array([np.floor(core / count), np.floor(mem_eff / count),
                         np.floor(ratio_eff / count)], np.float32)
    return count, per_inst


@dataclasses.dataclass
class AssignedPod:
    """A pod recently assumed on a node (podAssignCache entry,
    load_aware.go:260-267)."""

    pod: Pod
    node_name: str
    timestamp: float


class SnapshotBuilder:
    """Accumulates typed objects and emits a ClusterSnapshot (numpy pytree).

    Static capacities (max nodes/quotas/gangs/reservations/zones) are fixed at
    construction; rebuilding with the same capacities yields identically-shaped
    pytrees so jitted programs never recompile across versions.
    """

    def __init__(self, max_nodes: int, max_quotas: int = 8, max_gangs: int = 8,
                 max_reservations: int = 8, max_zones: int = 4,
                 max_gpu_inst: int = 0, max_aux_inst: int = 0,
                 max_selectors: int = 8, max_label_groups: int = 64,
                 max_tolerations: int = 8, max_taint_groups: int = 16,
                 max_spread_groups: int = 8, max_spread_domains: int = 16,
                 metric_expiration_s: float = DEFAULT_NODE_METRIC_EXPIRATION_S,
                 estimator_weights: Optional[Mapping[ResourceKind, float]] = None,
                 estimator_scaling: Optional[Mapping[ResourceKind, float]] = None,
                 score_with_aggregation: bool = False):
        self.max_nodes = max_nodes
        self.max_quotas = max_quotas
        self.max_gangs = max_gangs
        self.max_reservations = max_reservations
        self.max_zones = max_zones
        self.max_gpu_inst = max_gpu_inst
        self.max_aux_inst = max_aux_inst
        self.max_selectors = max_selectors
        self.max_label_groups = max_label_groups
        self.max_tolerations = max_tolerations
        self.max_taint_groups = max_taint_groups
        self.max_spread_groups = max_spread_groups
        self.max_spread_domains = max_spread_domains
        self._taint_groups: Dict[tuple, int] = {}
        # monotonically increasing delta sequence this builder stamps
        # into every emitted delta (snapshot/delta.py source_version) —
        # the store's replay guard keys on it
        self._delta_version = 0
        self.metric_expiration_s = metric_expiration_s
        # estimator config must match the LoadAware plugin args so that
        # PodBatch.estimated and the assign-cache columns agree with the
        # score kernel's expectations (types.go:44-58)
        self.estimator_weights = dict(estimator_weights or DEFAULT_RESOURCE_WEIGHTS)
        self.estimator_scaling = dict(estimator_scaling or DEFAULT_SCALING_FACTORS)
        # scoreWithAggregation(args.Aggregated) — affects which assigned
        # pods are estimated (load_aware.go:355-360 fourth clause)
        self.score_with_aggregation = score_with_aggregation

        self.nodes: List[Optional[Node]] = []  # None = freed row
        self.node_index: Dict[str, int] = {}
        # incremental topology state: freed rows for reuse, rows of
        # recently-removed nodes awaiting their zeroing delta, and the
        # persistent group/id tables that keep incremental rows
        # consistent with the last full build's snapshot
        self._free_rows: List[int] = []
        self._removed_rows: Dict[str, int] = {}
        self._label_groups: Dict[frozenset, int] = {}
        self._pcie_ids: Dict[str, int] = {}
        self.metrics: Dict[str, NodeMetric] = {}
        self.running_pods: List[Pod] = []
        self.assigned: List[AssignedPod] = []
        # assume-cache mirror: pods committed DEVICE-side whose watch
        # write-back has not arrived yet (scheduler cache assume,
        # scheduler_adapter.go) — they hold capacity in every recompute
        self.assumed_pods: List[Pod] = []
        self.quotas: List[ElasticQuota] = []
        self.quota_index: Dict[str, int] = {}
        self.gangs: List[PodGroup] = []
        self.gang_index: Dict[str, int] = {}
        self.gang_assumed: Dict[str, int] = {}
        self.gang_satisfied: Dict[str, bool] = {}
        self.reservations: List[Reservation] = []
        self.devices: Dict[str, Device] = {}

    # --- ingest -------------------------------------------------------------

    def add_node(self, node: Node) -> int:
        """Upsert: a known name updates its row in place; a new name
        reuses a freed row before growing (rows stay stable so device
        columns can be patched incrementally)."""
        existing = self.node_index.get(node.meta.name)
        if existing is not None:
            self.nodes[existing] = node
            return existing
        if self._free_rows:
            idx = self._free_rows.pop()
            self.nodes[idx] = node
        else:
            if len(self.nodes) >= self.max_nodes:
                raise ValueError("node capacity exceeded")
            idx = len(self.nodes)
            self.nodes.append(node)
        self.node_index[node.meta.name] = idx
        self._removed_rows.pop(node.meta.name, None)
        return idx

    def remove_node(self, name: str) -> int:
        """Free a node's row (the incremental topology path). The row id
        is stashed so topology_delta() emits its zeroing row; the row is
        reused by later add_node calls."""
        idx = self.node_index.pop(name)
        self.nodes[idx] = None
        self._free_rows.append(idx)
        self._removed_rows[name] = idx
        self.metrics.pop(name, None)
        self.devices.pop(name, None)
        return idx

    def set_node_metric(self, metric: NodeMetric) -> None:
        self.metrics[metric.node_name] = metric

    def add_running_pod(self, pod: Pod) -> None:
        """A pod already bound to a node (counts into `requested`)."""
        self.running_pods.append(pod)

    def add_assigned(self, pod: Pod, node_name: str,
                     timestamp: Optional[float] = None) -> None:
        """Record a recent assume (podAssignCache.assign)."""
        self.assigned.append(
            AssignedPod(pod, node_name, time.time() if timestamp is None
                        else timestamp))

    def set_assumed_pods(self, entries, estimation_entries=None) -> None:
        """Wholesale-mirror the hub's assume cache (ClusterInformerHub
        .note_assumed): `entries` is a sequence of (pod, timestamp) where
        each pod carries node_name + its fine-grained allocations (zone /
        GPU minors / aux instances / reservation) exactly as the device
        commit charged them — they hold CAPACITY (requested, NUMA,
        device grants, quota used; the scheduler cache's merged NodeInfo
        view). `estimation_entries` (default: `entries`) feeds the
        recently-assigned usage estimation instead (podAssignCache,
        load_aware.go:260-267) — it may additionally contain entries
        whose capacity charge already moved to the watched bound pod but
        whose usage the NodeMetric does not reflect yet. Replaces any
        earlier mirror."""
        self.assumed_pods = [p for p, _ in entries]
        if estimation_entries is None:
            estimation_entries = entries
        self.assigned = [AssignedPod(p, p.node_name, ts)
                         for p, ts in estimation_entries]

    def _capacity_pods(self):
        """Running pods plus assumed-but-not-yet-watched pods — the
        merged NodeInfo view the reference scheduler filters against
        (assume cache entries hold capacity until the watch delivers the
        bound pod; scheduler_adapter.go assume/forget). Yields
        (pod, is_assumed); an assumed uid the watch already delivered is
        skipped (the watched object carries the charge)."""
        seen = set()
        for p in self.running_pods:
            seen.add(p.meta.uid)
            yield p, False
        for p in self.assumed_pods:
            if p.meta.uid not in seen:
                yield p, True

    def add_quota(self, quota: ElasticQuota) -> int:
        if len(self.quotas) >= self.max_quotas:
            raise ValueError("quota capacity exceeded")
        idx = len(self.quotas)
        self.quotas.append(quota)
        self.quota_index[quota.meta.name] = idx
        return idx

    def add_gang(self, pg: PodGroup, assumed: int = 0,
                 satisfied: bool = False) -> int:
        """`satisfied` is the match-policy latch computed by GangDirectory
        (once-satisfied gangs short-circuit the gang gates, core.go:236)."""
        if len(self.gangs) >= self.max_gangs:
            raise ValueError("gang capacity exceeded")
        idx = len(self.gangs)
        self.gangs.append(pg)
        self.gang_index[pg.meta.name] = idx
        self.gang_assumed[pg.meta.name] = assumed
        self.gang_satisfied[pg.meta.name] = satisfied
        return idx

    def add_reservation(self, res: Reservation) -> None:
        if len(self.reservations) >= self.max_reservations:
            raise ValueError("reservation capacity exceeded")
        self.reservations.append(res)

    def add_device(self, device: Device) -> None:
        """Ingest a Device CR (per-node device inventory, deviceshare
        eventhandler_device.go)."""
        self.devices[device.node_name] = device

    # --- build: nodes -------------------------------------------------------

    def _label_group_id(self, node: Node) -> int:
        key = frozenset(node.meta.labels.items())
        groups = self._label_groups
        if key not in groups:
            if len(groups) >= self.max_label_groups:
                raise ValueError(
                    f"distinct node label sets exceed max_label_groups="
                    f"{self.max_label_groups}")
            groups[key] = len(groups)
        return groups[key]

    def _taint_group_id(self, node: Node) -> int:
        key = tuple(sorted((t.key, t.value, t.effect)
                           for t in node.taints))
        groups = self._taint_groups
        if key not in groups:
            if len(groups) >= self.max_taint_groups:
                raise ValueError(
                    f"distinct node taint sets exceed max_taint_groups="
                    f"{self.max_taint_groups}")
            groups[key] = len(groups)
        return groups[key]

    def _node_label_groups(self) -> Tuple[np.ndarray, Dict[frozenset, int]]:
        lab_ids = np.zeros((self.max_nodes,), np.int32)
        self._label_groups = {}
        for i, node in enumerate(self.nodes):
            if node is None:
                continue
            lab_ids[i] = self._label_group_id(node)
        return lab_ids, self._label_groups

    def _node_taint_groups(self) -> np.ndarray:
        """Partition nodes by taint set (TaintToleration gate; group 0 is
        always the untainted set so toleration-less pods ride row 0 of
        all-False matrices). Stashes the group dict for build() and for
        incremental topology rows."""
        ids = np.zeros((self.max_nodes,), np.int32)
        self._taint_groups = {(): 0}
        for i, node in enumerate(self.nodes):
            if node is None:
                continue
            ids[i] = self._taint_group_id(node)
        return ids

    def _fill_identity_row(self, node: Node, i: int, alloc, schedulable,
                           cpu_amp, numa_cap, numa_valid,
                           numa_policy) -> None:
        """One node's identity columns, written into row i of the given
        arrays — shared by the full build and topology_delta so the two
        paths cannot drift."""
        z = self.max_zones
        alloc[i] = resource_vec(node.allocatable)
        schedulable[i] = not node.unschedulable
        # amplification ratio (resource-amplification-ratio annotation,
        # published by the node webhook alongside AMPLIFIED allocatable;
        # nodenumaresource util.go:65-85) — the shared parser, so
        # host preemption's dry run and the device gate agree.
        cpu_amp[i] = node_cpu_amplification_ratio(node.meta.annotations)
        if node.topology is not None:
            for j, zone in enumerate(node.topology.zones[:z]):
                numa_cap[i, j, 0] = zone.cpus_milli
                numa_cap[i, j, 1] = zone.memory_mib
                numa_valid[i, j] = True
            # kubelet/NRT topology policy -> the scheduler-side
            # topology manager (numa_aware.go GetNodeNUMATopologyPolicy)
            numa_policy[i] = numa_policy_code(node.topology.policy)

    def build_nodes(self, now: Optional[float] = None) -> Tuple[NodeState, Dict[frozenset, int]]:
        now = time.time() if now is None else now
        n, r, z = self.max_nodes, NUM_RESOURCES, self.max_zones
        alloc = np.zeros((n, r), np.float32)
        requested = np.zeros((n, r), np.float32)
        usage = np.zeros((n, r), np.float32)
        prod_usage = np.zeros((n, r), np.float32)
        agg = np.zeros((n, NUM_AGG, r), np.float32)
        assigned_est = np.zeros((n, r), np.float32)
        assigned_corr = np.zeros((n, r), np.float32)
        prod_assigned_est = np.zeros((n, r), np.float32)
        prod_assigned_corr = np.zeros((n, r), np.float32)
        fresh = np.zeros((n,), bool)
        has_agg = np.zeros((n,), bool)
        schedulable = np.zeros((n,), bool)
        numa_cap = np.zeros((n, z, 2), np.float32)
        numa_valid = np.zeros((n, z), bool)
        numa_policy = np.zeros((n,), np.int32)

        cpu_amp = np.ones((n,), np.float32)
        for i, node in enumerate(self.nodes):
            if node is None:
                continue
            self._fill_identity_row(node, i, alloc, schedulable, cpu_amp,
                                    numa_cap, numa_valid, numa_policy)

        numa_used = np.zeros((n, z, 2), np.float32)
        res_by_name = {r.meta.name: r for r in self.reservations}
        for pod, is_assumed in self._capacity_pods():
            idx = self.node_index.get(pod.node_name)
            if idx is not None:
                rv = resource_vec(pod.requests)
                # restore zone usage of running NUMA-bound pods from their
                # resource-status annotation (nodenumaresource
                # resource_manager.go rebuilds allocations the same way).
                # Zone charges stay RAW — zone capacities are raw and the
                # in-cycle commit charges zones raw too (core.py amplified
                # CPU: ratio cancels in the zone fit)
                zi = pod.allocated_numa_zone
                if pod.required_cpu_bind and 0 <= zi < z:
                    numa_used[idx, zi, 0] += rv[int(ResourceKind.CPU)]
                    numa_used[idx, zi, 1] += rv[int(ResourceKind.MEMORY)]
                if is_assumed and pod.reservation_name:
                    # an assumed reservation CONSUMER drew from the slot
                    # hold, not the node pool (core.py res_slot commit);
                    # build_reservations subtracts it from the hold's
                    # free instead — charging requested here would
                    # double-count until the CR's allocated catches up.
                    # Skip ONLY under build_reservations' exact subtract
                    # condition: a consumer of a non-Available (e.g.
                    # Succeeded allocate-once) or already-accounted
                    # (current_owners) reservation has no hold absorbing
                    # its charge and must hit node requested normally.
                    res = res_by_name.get(pod.reservation_name)
                    if (res is not None and res.phase == "Available"
                            and res.node_name == pod.node_name
                            and pod.meta.uid not in res.current_owners):
                        continue
                if pod.required_cpu_bind and cpu_amp[idx] > 1.0:
                    # exclusive cores cost amplified CPU against the
                    # amplified allocatable (filterAmplifiedCPUs's
                    # re-amplification of allocatedMilliCPU)
                    rv = rv.copy()
                    rv[int(ResourceKind.CPU)] *= cpu_amp[idx]
                requested[idx] += rv

        # An Available reservation is a "reserve pod": its requests are
        # charged to node requested up front (reservation/transformer.go
        # restoreUnmatchedReservations keeps net accounting at exactly the
        # reservation's allocatable). Consumers appear as running pods
        # charging their own requests, so only the unallocated remainder is
        # charged here; in-cycle consumers skip the node charge instead
        # (scheduler core res_slot handling).
        for res in self.reservations:
            if res.phase == "Available" and res.node_name:
                idx = self.node_index.get(res.node_name)
                if idx is not None:
                    requested[idx] += np.maximum(
                        resource_vec(res.requests)
                        - resource_vec(res.allocated), 0.0)

        # NodeMetric columns + the assign-cache adjustment.
        pods_per_node = self._pods_per_node()
        for name, metric in self.metrics.items():
            i = self.node_index.get(name)
            if i is None:
                continue
            row = self._metric_row(name, metric, now, pods_per_node)
            if row is None:
                continue
            (fresh[i], usage[i], prod_usage[i], agg[i], has_agg[i],
             assigned_est[i], assigned_corr[i], prod_assigned_est[i],
             prod_assigned_corr[i]) = row

        lab_ids, groups = self._node_label_groups()
        state = NodeState(
            allocatable=alloc, requested=requested, usage=usage,
            prod_usage=prod_usage, agg_usage=agg,
            assigned_estimated=assigned_est,
            assigned_correction=assigned_corr,
            prod_assigned_estimated=prod_assigned_est,
            prod_assigned_correction=prod_assigned_corr,
            metric_fresh=fresh,
            has_agg=has_agg, schedulable=schedulable, label_group=lab_ids,
            numa_cap=numa_cap,
            numa_free=np.maximum(numa_cap - numa_used, 0.0),
            numa_valid=numa_valid,
            numa_policy=numa_policy,
            cpu_amplification=cpu_amp,
            taint_group=self._node_taint_groups(),
        )
        return state, groups

    # --- build: quotas / gangs / reservations -------------------------------

    def build_quotas(self) -> QuotaState:
        q, r = self.max_quotas, NUM_RESOURCES
        qmin = np.zeros((q, r), np.float32)
        qmax = np.full((q, r), np.inf, np.float32)
        weight = np.zeros((q, r), np.float32)
        allow_lent = np.ones((q,), bool)
        parent = np.full((q,), -1, np.int32)
        ancestors = np.zeros((q, q), bool)
        used = np.zeros((q, r), np.float32)
        valid = np.zeros((q,), bool)
        for i, quota in enumerate(self.quotas):
            qmin[i] = resource_vec(quota.min)
            mv = resource_vec(quota.max)
            qmax[i] = np.where(mv > 0, mv, np.inf)
            wv = resource_vec(quota.shared_weight)
            # sharedWeight defaults to max (quota_info semantics)
            weight[i] = np.where(wv > 0, wv, np.where(np.isinf(qmax[i]), 1.0,
                                                      qmax[i]))
            parent[i] = self.quota_index.get(quota.parent, -1)
            allow_lent[i] = quota.allow_lent_resource
            valid[i] = True
        depth_anc = np.full((q, MAX_QUOTA_DEPTH), -1, np.int32)
        for i in range(len(self.quotas)):
            chain = []
            j = i
            while j >= 0:
                if j in chain:
                    raise ValueError(
                        f"quota parent cycle involving "
                        f"{self.quotas[i].meta.name!r}")
                ancestors[i, j] = True
                chain.append(j)
                j = int(parent[j])
            if len(chain) > MAX_QUOTA_DEPTH:
                # static device shapes cap the tree depth; reject loudly
                # rather than silently skipping a level's enforcement
                raise ValueError(
                    f"quota tree depth {len(chain)} exceeds MAX_QUOTA_DEPTH="
                    f"{MAX_QUOTA_DEPTH} at {self.quotas[i].meta.name!r}")
            # chain is leaf->root; depth_anc[d] = ancestor at depth d from root
            for d, a in enumerate(reversed(chain)):
                depth_anc[i, d] = a
        direct_used = np.zeros((q, r), np.float32)
        # assumed pods count: the device commit already charged quota
        # used for them (core.py), and a rebuild must not return it
        for pod, _ in self._capacity_pods():
            qi = self.quota_index.get(pod.quota_name, -1)
            if qi >= 0:
                direct_used[qi] += resource_vec(pod.requests)
        # propagate used up the tree: used[a] = Σ direct_used[q] over quotas q
        # with a ∈ ancestors(q) (GroupQuotaManager updateGroupDeltaUsed walk)
        used = ancestors.astype(np.float32).T @ direct_used
        # demand is DIRECT per-quota pod demand; ops.waterfill propagates it
        # bottom-up with the per-level min/max clamp (limitedRequest). The
        # scheduler adds pending-batch demand (ops.quota_demand) first.
        return QuotaState(min=qmin, max=qmax, shared_weight=weight,
                          parent=parent, ancestors=ancestors,
                          depth_ancestor=depth_anc, used=used,
                          demand=direct_used.copy(), allow_lent=allow_lent,
                          runtime=np.full((q, r), np.inf, np.float32),
                          valid=valid)

    def build_gangs(self) -> GangState:
        g = self.max_gangs
        min_member = np.ones((g,), np.int32)
        member_count = np.zeros((g,), np.int32)
        assumed = np.zeros((g,), np.int32)
        strict = np.ones((g,), bool)
        satisfied = np.zeros((g,), bool)
        valid = np.zeros((g,), bool)
        for i, pg in enumerate(self.gangs):
            min_member[i] = pg.min_member
            member_count[i] = pg.total_member
            assumed[i] = self.gang_assumed.get(pg.meta.name, 0)
            strict[i] = pg.mode != "NonStrict"
            satisfied[i] = self.gang_satisfied.get(pg.meta.name, False)
            valid[i] = True
        return GangState(min_member=min_member, member_count=member_count,
                         assumed=assumed, strict=strict, satisfied=satisfied,
                         valid=valid)

    def _pods_per_node(self) -> Dict[str, List[AssignedPod]]:
        out: Dict[str, List[AssignedPod]] = {}
        for ap in self.assigned:
            out.setdefault(ap.node_name, []).append(ap)
        return out

    def _metric_row(self, name: str, metric: NodeMetric, now: float,
                    pods_per_node: Dict[str, List[AssignedPod]]):
        """One node's metric-derived columns: (fresh, usage, prod_usage,
        agg [NUM_AGG, R], has_agg, assigned_est, assigned_corr,
        prod_assigned_est, prod_assigned_corr), or None when expired.
        Shared by the full rebuild and the per-node metric delta so the two
        paths cannot drift."""
        if metric.is_expired(self.metric_expiration_s, now):
            return None
        r = NUM_RESOURCES
        usage = resource_vec(metric.node_usage)
        prod_usage = np.zeros((r,), np.float32)
        agg = np.zeros((NUM_AGG, r), np.float32)
        has_agg = False
        pod_usages = {pm.namespaced_name: resource_vec(pm.usage)
                      for pm in metric.pods_metric}
        for pm in metric.pods_metric:
            if pm.priority_class is PriorityClass.PROD:
                prod_usage += resource_vec(pm.usage)
        for a, agg_type in enumerate(AGG_TYPES):
            au = metric.aggregated_usage(agg_type)
            if au is not None:
                agg[a] = resource_vec(au)
                has_agg = True

        # estimatedAssignedPodUsed (load_aware.go:340-378): recently
        # assumed pods not yet visible in the NodeMetric are estimated;
        # those visible-but-recent use max(estimate, usage). Their
        # reported usage is recorded as a correction the score kernel
        # subtracts from the node usage source (load_aware.go:300-315).
        assigned_est = np.zeros((r,), np.float32)
        assigned_corr = np.zeros((r,), np.float32)
        prod_est = np.zeros((r,), np.float32)
        prod_corr = np.zeros((r,), np.float32)
        interval = metric.report_interval_seconds or DEFAULT_REPORT_INTERVAL_S
        for ap in pods_per_node.get(name, []):
            key = ap.pod.meta.namespaced_name
            pod_usage = pod_usages.get(key)
            recent = (ap.timestamp > metric.update_time
                      or metric.update_time - ap.timestamp < interval)
            # fourth clause (load_aware.go:355-360): score aggregation
            # configured but this node has no percentile data -> the
            # usage source contributes nothing, so estimate everything
            agg_missing = self.score_with_aggregation and not metric.aggregated
            is_prod = ap.pod.priority_class is PriorityClass.PROD
            if pod_usage is None or recent or agg_missing:
                est = estimate_pod(ap.pod, self.estimator_scaling,
                                   self.estimator_weights)
                if pod_usage is not None:
                    est = np.maximum(est, pod_usage)
                    assigned_corr += pod_usage
                    if is_prod:
                        prod_corr += pod_usage
                assigned_est += est
                if is_prod:
                    prod_est += est
        return (True, usage, prod_usage, agg, has_agg,
                assigned_est, assigned_corr, prod_est, prod_corr)

    def resume_delta_version(self, version: int) -> None:
        """Fast-forward the builder's delta sequence to at least a
        restored store's `applied_delta_version` watermark
        (SnapshotStore.restore), so a producer restarted from a
        checkpoint stamps its NEXT delta above everything the
        checkpoint already contains — without this, the restarted
        sequence restarts at 1 and the store's replay guard (rightly)
        rejects every fresh delta as stale."""
        self._delta_version = max(self._delta_version, int(version))

    def _next_delta_version(self, version: Optional[int]) -> np.ndarray:
        """Stamp for an emitted delta: the explicit `version` wins (and
        advances the high-water mark), else the builder's own sequence
        increments. The store rejects replays against it."""
        if version is None:
            self._delta_version += 1
            version = self._delta_version
        else:
            self._delta_version = max(self._delta_version, int(version))
        return np.asarray(int(version), np.int32)

    def metric_delta(self, names: Sequence[str], now: Optional[float] = None,
                     pad_to: Optional[int] = None,
                     version: Optional[int] = None) -> "NodeMetricDelta":
        """Per-node metric ingest: the changed nodes' metric-derived
        columns as a fixed-capacity delta the store applies DEVICE-SIDE
        (snapshot/delta.py) — no full column re-upload. `pad_to` fixes the
        delta capacity so repeated ingests hit one compiled program."""
        from koordinator_tpu.snapshot.delta import NodeMetricDelta

        now = time.time() if now is None else now
        k = pad_to if pad_to is not None else max(len(names), 1)
        if len(names) > k:
            raise ValueError(f"{len(names)} metric updates exceed pad_to={k}")
        r = NUM_RESOURCES
        idx = np.full((k,), -1, np.int32)
        fresh = np.zeros((k,), bool)
        usage = np.zeros((k, r), np.float32)
        prod_usage = np.zeros((k, r), np.float32)
        agg = np.zeros((k, NUM_AGG, r), np.float32)
        has_agg = np.zeros((k,), bool)
        est = np.zeros((k, r), np.float32)
        corr = np.zeros((k, r), np.float32)
        p_est = np.zeros((k, r), np.float32)
        p_corr = np.zeros((k, r), np.float32)
        pods_per_node = self._pods_per_node()
        for j, name in enumerate(names):
            i = self.node_index.get(name)
            metric = self.metrics.get(name)
            if i is None or metric is None:
                continue
            idx[j] = i
            row = self._metric_row(name, metric, now, pods_per_node)
            if row is None:
                continue  # expired: row stays zero, fresh False
            (fresh[j], usage[j], prod_usage[j], agg[j], has_agg[j],
             est[j], corr[j], p_est[j], p_corr[j]) = row
        return NodeMetricDelta(
            idx=idx, metric_fresh=fresh, usage=usage, prod_usage=prod_usage,
            agg_usage=agg, has_agg=has_agg, assigned_estimated=est,
            assigned_correction=corr, prod_assigned_estimated=p_est,
            prod_assigned_correction=p_corr,
            source_version=self._next_delta_version(version))

    def topology_delta(self, names: Sequence[str],
                       now: Optional[float] = None,
                       pad_to: Optional[int] = None,
                       version: Optional[int] = None) -> "NodeTopologyDelta":
        """Node add/remove/update as an O(K) column delta (snapshot/
        delta.py NodeTopologyDelta): for each name, the node's complete
        identity + device + metric row exactly as a full rebuild would
        produce it — a name no longer present emits its zeroing row
        (remove_node stashed the freed row id). Row-for-row parity with
        the full rebuild is pinned by tests/test_topology_delta.py.

        Cost: O(K) array rows plus one linear pass over running pods /
        reservations restricted to the K nodes — never O(max_nodes)."""
        from koordinator_tpu.snapshot.delta import (
            NodeMetricDelta,
            NodeTopologyDelta,
        )

        now = time.time() if now is None else now
        k = pad_to if pad_to is not None else max(len(names), 1)
        if len(names) > k:
            raise ValueError(
                f"{len(names)} topology updates exceed pad_to={k}")
        # a node hosting an Available reservation carries instance/zone
        # HOLDS that only build_reservations can subtract — and a
        # removed node may still be referenced by ReservationState.node
        # (row indices: a reused row would silently re-target it).
        # Both demand the rebuild path; raising routes the syncer there.
        touched = set(names)
        for res in self.reservations:
            if res.phase == "Available" and res.node_name in touched:
                raise ValueError(
                    f"node {res.node_name!r} hosts an Available "
                    f"reservation; topology rows cannot carry "
                    f"reservation holds — rebuild")
        r, z = NUM_RESOURCES, self.max_zones
        gi, aj = self.max_gpu_inst, self.max_aux_inst
        f32 = np.float32
        idx = np.full((k,), -1, np.int32)
        alloc = np.zeros((k, r), f32)
        requested = np.zeros((k, r), f32)
        schedulable = np.zeros((k,), bool)
        label_group = np.zeros((k,), np.int32)
        taint_group = np.zeros((k,), np.int32)
        numa_cap = np.zeros((k, z, 2), f32)
        numa_valid = np.zeros((k, z), bool)
        numa_policy = np.zeros((k,), np.int32)
        cpu_amp = np.ones((k,), f32)
        gpu_total = np.zeros((k, NUM_DEV_DIMS), f32)
        gpu_free = np.zeros((k, gi, NUM_DEV_DIMS), f32)
        gpu_valid = np.zeros((k, gi), bool)
        gpu_numa = np.full((k, gi), -1, np.int32)
        gpu_pcie = np.full((k, gi), -1, np.int32)
        aux_free = np.zeros((k, NUM_AUX_TYPES, aj), f32)
        aux_valid = np.zeros((k, NUM_AUX_TYPES, aj), bool)

        present = {n: j for j, n in enumerate(names)
                   if n in self.node_index}
        # one filtered pass: requested + zone usage of running AND
        # assumed pods / reservations landing on the K nodes (mirrors
        # build_nodes; ADVICE r4 — a node heartbeat ingest must not
        # erase device-side commit charges carried by assumed pods).
        # Assumed reservation CONSUMERS cannot appear here: they imply
        # an Available reservation on the node, which the guard above
        # already routed to the rebuild.
        numa_used = np.zeros((k, z, 2), f32)
        amp_of = {n: node_cpu_amplification_ratio(
            self.nodes[self.node_index[n]].meta.annotations)
            for n in present}
        running_here: Dict[str, List[Pod]] = {}
        for pod, _ in self._capacity_pods():
            j = present.get(pod.node_name)
            if j is None:
                continue
            running_here.setdefault(pod.node_name, []).append(pod)
            rv = resource_vec(pod.requests)
            zi = pod.allocated_numa_zone
            if pod.required_cpu_bind and 0 <= zi < z:
                numa_used[j, zi, 0] += rv[int(ResourceKind.CPU)]
                numa_used[j, zi, 1] += rv[int(ResourceKind.MEMORY)]
            if pod.required_cpu_bind and amp_of[pod.node_name] > 1.0:
                rv = rv.copy()
                rv[int(ResourceKind.CPU)] *= amp_of[pod.node_name]
            requested[j] += rv
        for res in self.reservations:
            j = present.get(res.node_name)
            if j is not None and res.phase == "Available":
                requested[j] += np.maximum(
                    resource_vec(res.requests)
                    - resource_vec(res.allocated), 0.0)

        pods_per_node = self._pods_per_node()
        fresh = np.zeros((k,), bool)
        usage = np.zeros((k, r), f32)
        prod_usage = np.zeros((k, r), f32)
        agg = np.zeros((k, NUM_AGG, r), f32)
        has_agg = np.zeros((k,), bool)
        est = np.zeros((k, r), f32)
        corr = np.zeros((k, r), f32)
        p_est = np.zeros((k, r), f32)
        p_corr = np.zeros((k, r), f32)
        for jrow, name in enumerate(names):
            ni = self.node_index.get(name)
            if ni is None:
                freed = self._removed_rows.pop(name, None)
                # a freed row already REUSED by another node in this
                # same delta window must not also get a zeroing row:
                # duplicate scatter targets are nondeterministic in
                # jnp .at[].set — the occupant's row supersedes it
                if freed is not None and self.nodes[freed] is None:
                    idx[jrow] = freed  # zeroing row: defaults stand
                continue
            node = self.nodes[ni]
            idx[jrow] = ni
            self._fill_identity_row(node, jrow, alloc, schedulable,
                                    cpu_amp, numa_cap, numa_valid,
                                    numa_policy)
            label_group[jrow] = self._label_group_id(node)
            taint_group[jrow] = self._taint_group_id(node)
            device = self.devices.get(name)
            if device is not None:
                self._fill_device_row(name, device, jrow, gpu_total,
                                      gpu_free, gpu_valid, gpu_numa,
                                      gpu_pcie, aux_free, aux_valid)
                # running-pod grants shrink instance free, and aggregate
                # device capacity rides allocatable — the same per-row
                # helpers the full build uses
                for pod in running_here.get(name, []):
                    self._subtract_pod_grants(pod, jrow, gpu_total,
                                              gpu_free, aux_free)
                self._merge_device_allocatable(device, jrow, alloc,
                                               gpu_total, gpu_valid)
            metric = self.metrics.get(name)
            if metric is not None:
                row = self._metric_row(name, metric, now, pods_per_node)
                if row is not None:
                    (fresh[jrow], usage[jrow], prod_usage[jrow],
                     agg[jrow], has_agg[jrow], est[jrow], corr[jrow],
                     p_est[jrow], p_corr[jrow]) = row
        return NodeTopologyDelta(
            idx=idx, allocatable=alloc, requested=requested,
            schedulable=schedulable, label_group=label_group,
            taint_group=taint_group, numa_cap=numa_cap,
            numa_free=np.maximum(numa_cap - numa_used, 0.0),
            numa_valid=numa_valid, numa_policy=numa_policy,
            cpu_amplification=cpu_amp,
            gpu_total=gpu_total, gpu_free=gpu_free, gpu_valid=gpu_valid,
            gpu_numa=gpu_numa, gpu_pcie=gpu_pcie,
            aux_free=aux_free, aux_valid=aux_valid,
            metric=NodeMetricDelta(
                idx=idx, metric_fresh=fresh, usage=usage,
                prod_usage=prod_usage, agg_usage=agg, has_agg=has_agg,
                assigned_estimated=est, assigned_correction=corr,
                prod_assigned_estimated=p_est,
                prod_assigned_correction=p_corr),
            source_version=self._next_delta_version(version))

    def build_reservations(self, owner_groups: Dict[str, int],
                           nodes: "NodeState",
                           devices: "DeviceState") -> ReservationState:
        """Columnarize Available reservations, including their fine-grained
        holds (reserved GPU minors / NUMA cpuset zone). The REMAINING hold
        (reservation grant minus what consumers already drew) is moved from
        the node pools into per-slot pools, so non-owners cannot take it
        and consumers draw exactly the reserved minors/zone
        (transformer.go:240-291 restoreMatchedReservation; deviceshare /
        nodenumaresource ReservationRestorePlugin)."""
        v, r = self.max_reservations, NUM_RESOURCES
        n_inst = devices.gpu_free.shape[1]
        n_zones = nodes.numa_cap.shape[1]
        node = np.full((v,), -1, np.int32)
        free = np.zeros((v, r), np.float32)
        owner = np.full((v,), -1, np.int32)
        once = np.ones((v,), bool)
        valid = np.zeros((v,), bool)
        gpu_free_v = np.zeros((v, n_inst, NUM_DEV_DIMS), np.float32)
        gpu_valid_v = np.zeros((v, n_inst), bool)
        numa_free_v = np.zeros((v, n_zones, 2), np.float32)
        numa_valid_v = np.zeros((v, n_zones), bool)

        consumers: Dict[str, List[Pod]] = {}
        assumed_consumers: Dict[str, List[Pod]] = {}
        for pod, is_assumed in self._capacity_pods():
            if pod.reservation_name:
                consumers.setdefault(pod.reservation_name, []).append(pod)
                if is_assumed:
                    assumed_consumers.setdefault(
                        pod.reservation_name, []).append(pod)

        for i, res in enumerate(self.reservations):
            if res.phase != "Available" or not res.node_name:
                continue
            ni = self.node_index.get(res.node_name)
            if ni is None:
                continue
            node[i] = ni
            free[i] = resource_vec(res.requests) - resource_vec(res.allocated)
            # assumed consumers drew from the hold device-side but are
            # not in the CR's `allocated` yet — subtract them here (and
            # skip their node `requested` charge, see build_nodes).
            # current_owners is the belt: a consumer the CR already
            # accounts for must not be subtracted twice (the hub retires
            # such assumes on the reservation watch, but compositions
            # feeding the builder directly bypass that).
            for c in assumed_consumers.get(res.meta.name, ()):
                if (c.node_name == res.node_name
                        and c.meta.uid not in res.current_owners):
                    free[i] -= resource_vec(c.requests)
            free[i] = np.maximum(free[i], 0.0)
            key = _selector_key(res.owner_label_selector)
            owner[i] = owner_groups.setdefault(key, len(owner_groups))
            once[i] = res.allocate_once
            valid[i] = True
            consuming = [c for c in consumers.get(res.meta.name, ())
                         if c.node_name == res.node_name]

            if res.allocated_gpu_minors:
                pseudo = Pod(requests=dict(res.requests),
                             gpu_memory_ratio=res.gpu_memory_ratio)
                _, per_inst = gpu_per_instance_host(
                    devices.gpu_total[ni, DEV_MEM], pseudo)
                for m in res.allocated_gpu_minors:
                    if 0 <= m < n_inst:
                        gpu_free_v[i, m] = per_inst
                        gpu_valid_v[i, m] = True
                for c in consuming:
                    _, c_per = gpu_per_instance_host(
                        devices.gpu_total[ni, DEV_MEM], c)
                    for m in c.allocated_gpu_minors:
                        if 0 <= m < n_inst and gpu_valid_v[i, m]:
                            gpu_free_v[i, m] = np.maximum(
                                gpu_free_v[i, m] - c_per, 0.0)
                # the remaining hold leaves the node pool (consumers'
                # takes were already subtracted by build_devices, so
                # node free drops by exactly the full reserved amount)
                for m in res.allocated_gpu_minors:
                    if 0 <= m < n_inst:
                        devices.gpu_free[ni, m] = np.maximum(
                            devices.gpu_free[ni, m] - gpu_free_v[i, m], 0.0)

            zi = res.allocated_numa_zone
            if res.required_cpu_bind and 0 <= zi < n_zones:
                rv = resource_vec(res.requests)
                hold = np.array([rv[int(ResourceKind.CPU)],
                                 rv[int(ResourceKind.MEMORY)]], np.float32)
                for c in consuming:
                    if c.required_cpu_bind and c.allocated_numa_zone == zi:
                        cv = resource_vec(c.requests)
                        hold -= (cv[int(ResourceKind.CPU)],
                                 cv[int(ResourceKind.MEMORY)])
                hold = np.maximum(hold, 0.0)
                numa_free_v[i, zi] = hold
                numa_valid_v[i, zi] = True
                nodes.numa_free[ni, zi] = np.maximum(
                    nodes.numa_free[ni, zi] - hold, 0.0)

        return ReservationState(node=node, free=free, owner_group=owner,
                                allocate_once=once, valid=valid,
                                gpu_free=gpu_free_v, gpu_valid=gpu_valid_v,
                                numa_free=numa_free_v,
                                numa_valid=numa_valid_v)

    def _fill_device_row(self, node_name: str, device: Device, ni: int,
                         gpu_total, gpu_free, gpu_valid, gpu_numa,
                         gpu_pcie, aux_free, aux_valid) -> None:
        """One node's Device CR, written into row ni of the given arrays
        — shared by build_devices and topology_delta. PCIe root ids come
        from the persistent self._pcie_ids table so incremental rows
        stay consistent with the snapshot's existing gpu_pcie values.

        Columns are indexed by DeviceInfo.minor — running-pod restore
        and the scheduler's gpu_take/aux_inst outputs (the device-
        allocation annotation) address instances by minor, so list
        position must not matter."""
        i, j = self.max_gpu_inst, self.max_aux_inst
        aux_pool = {"rdma": AUX_RDMA, "fpga": AUX_FPGA}
        seen_gpu = set()
        seen_aux = {AUX_RDMA: set(), AUX_FPGA: set()}
        for info in device.devices:
            if info.type == "gpu":
                m = info.minor
                if not 0 <= m < i:
                    raise ValueError(
                        f"GPU minor {m} on {node_name!r} outside "
                        f"max_gpu_inst={i}")
                if m in seen_gpu:
                    raise ValueError(
                        f"duplicate GPU minor {m} on {node_name!r}")
                seen_gpu.add(m)
                mem = float(info.resources.get(ResourceKind.GPU_MEMORY,
                                               0.0))
                # gpu_total[ni] is the per-node memory↔ratio conversion
                # basis (memory per 100% of one instance); mixed GPU
                # sizes on one node have no single basis, so reject
                # them instead of silently keeping the last value
                if seen_gpu != {m} and gpu_total[ni][1] != mem:
                    raise ValueError(
                        f"heterogeneous GPU memory on {node_name!r}: "
                        f"{gpu_total[ni][1]} vs {mem} (minor {m})")
                gpu_total[ni] = (100.0, mem, 100.0)
                if info.health:
                    gpu_free[ni, m] = (100.0, mem, 100.0)
                    gpu_valid[ni, m] = True
                gpu_numa[ni, m] = info.numa_node
                if info.pcie_id:
                    gpu_pcie[ni, m] = self._pcie_ids.setdefault(
                        info.pcie_id, len(self._pcie_ids))
            elif info.type in aux_pool:
                t = aux_pool[info.type]
                m = info.minor
                if not 0 <= m < j:
                    raise ValueError(
                        f"{info.type} minor {m} on {node_name!r} "
                        f"outside max_aux_inst={j}")
                if m in seen_aux[t]:
                    raise ValueError(
                        f"duplicate {info.type} minor {m} on "
                        f"{node_name!r}")
                seen_aux[t].add(m)
                if info.health:
                    kind = (ResourceKind.RDMA if t == AUX_RDMA
                            else ResourceKind.FPGA)
                    aux_free[ni, t, m] = float(
                        info.resources.get(kind, 100.0))
                    aux_valid[ni, t, m] = True

    def _subtract_pod_grants(self, pod: Pod, ni: int, gpu_total,
                             gpu_free, aux_free) -> None:
        """A running pod's granted device instances (the device-
        allocation annotation) shrink row ni's free pools — shared by
        build_devices and topology_delta."""
        i, j = self.max_gpu_inst, self.max_aux_inst
        if pod.allocated_gpu_minors:
            _, per_inst = gpu_per_instance_host(
                gpu_total[ni, DEV_MEM], pod)
            for minor in pod.allocated_gpu_minors:
                if 0 <= minor < i:
                    gpu_free[ni, minor] = np.maximum(
                        gpu_free[ni, minor] - per_inst, 0.0)
        for t, inst in ((AUX_RDMA, pod.allocated_rdma_inst),
                        (AUX_FPGA, pod.allocated_fpga_inst)):
            kind = ResourceKind.RDMA if t == AUX_RDMA else ResourceKind.FPGA
            req = float(pod.requests.get(kind, 0.0))
            if req > 0 and 0 <= inst < j:
                aux_free[ni, t, inst] = max(aux_free[ni, t, inst] - req,
                                            0.0)

    def _merge_device_allocatable(self, device: Device, ni: int, alloc,
                                  gpu_total, gpu_valid) -> None:
        """Aggregate device capacity rides node allocatable (the device
        plugin reports extended resources) unless the Node already did
        — shared by build() and topology_delta."""
        gc, gm = int(ResourceKind.GPU_CORE), int(ResourceKind.GPU_MEMORY)
        vc = float(gpu_valid[ni].sum())
        if alloc[ni, gc] == 0:
            alloc[ni, gc] = gpu_total[ni, DEV_CORE] * vc
        if alloc[ni, gm] == 0:
            alloc[ni, gm] = gpu_total[ni, DEV_MEM] * vc
        for kind, typ in ((ResourceKind.RDMA, "rdma"),
                          (ResourceKind.FPGA, "fpga")):
            kk = int(kind)
            if alloc[ni, kk] == 0:
                alloc[ni, kk] = sum(
                    float(info.resources.get(kind, 100.0))
                    for info in device.devices
                    if info.type == typ and info.health)

    def build_devices(self) -> DeviceState:
        """Columnarize Device CRs; running pods' granted instances (the
        device-allocation annotation) are subtracted from free, mirroring
        how deviceshare eventhandler_pod.go rebuilds nodeDeviceCache."""
        n, i, j = self.max_nodes, self.max_gpu_inst, self.max_aux_inst
        f32 = np.float32
        gpu_total = np.zeros((n, NUM_DEV_DIMS), f32)
        gpu_free = np.zeros((n, i, NUM_DEV_DIMS), f32)
        gpu_valid = np.zeros((n, i), bool)
        gpu_numa = np.full((n, i), -1, np.int32)
        gpu_pcie = np.full((n, i), -1, np.int32)
        aux_free = np.zeros((n, NUM_AUX_TYPES, j), f32)
        aux_valid = np.zeros((n, NUM_AUX_TYPES, j), bool)
        self._pcie_ids = {}
        for node_name, device in self.devices.items():
            ni = self.node_index.get(node_name)
            if ni is None:
                continue
            self._fill_device_row(node_name, device, ni, gpu_total,
                                  gpu_free, gpu_valid, gpu_numa, gpu_pcie,
                                  aux_free, aux_valid)
        for pod, _ in self._capacity_pods():
            ni = self.node_index.get(pod.node_name)
            if ni is None:
                continue
            self._subtract_pod_grants(pod, ni, gpu_total, gpu_free,
                                      aux_free)
        return DeviceState(gpu_total=gpu_total, gpu_free=gpu_free,
                           gpu_valid=gpu_valid, gpu_numa=gpu_numa,
                           gpu_pcie=gpu_pcie, aux_free=aux_free,
                           aux_valid=aux_valid)

    def build(self, now: Optional[float] = None,
              version: int = 0) -> Tuple[ClusterSnapshot, "BuildContext"]:
        nodes, label_groups = self.build_nodes(now)
        devices = self.build_devices()
        # aggregate device capacity rides node allocatable, feeding the
        # cheap node-level fit gate before the instance gates
        alloc = nodes.allocatable
        for node_name, device in self.devices.items():
            ni = self.node_index.get(node_name)
            if ni is None:
                continue
            self._merge_device_allocatable(device, ni, alloc,
                                           devices.gpu_total,
                                           devices.gpu_valid)
        owner_groups: Dict[str, int] = {}
        # reservations may move remaining fine-grained holds out of the
        # node/device pools, so build them against the materialized arrays
        reservations = self.build_reservations(owner_groups, nodes, devices)
        snap = ClusterSnapshot(
            nodes=nodes,
            quotas=self.build_quotas(),
            gangs=self.build_gangs(),
            reservations=reservations,
            devices=devices,
            version=np.int32(version),
        )
        # ctx holds the LIVE group tables (not copies): taint/label
        # groups minted later by the incremental topology_delta path
        # must reach build_pod_batch's matrices, or fresh taints would
        # be silently unenforced until the next full rebuild
        ctx = BuildContext(self, label_groups, owner_groups,
                           self._taint_groups)
        return snap, ctx

    # --- build: pod batch ---------------------------------------------------

    def build_pod_batch(self, pods: Sequence[Pod], ctx: "BuildContext",
                        max_pods: Optional[int] = None) -> PodBatch:
        p = max_pods or len(pods)
        if len(pods) > p:
            raise ValueError("pod batch exceeds capacity")
        r = NUM_RESOURCES
        requests = np.zeros((p, r), np.float32)
        estimated = np.zeros((p, r), np.float32)
        qos = np.zeros((p,), np.int8)
        prio_class = np.zeros((p,), np.int8)
        prio = np.zeros((p,), np.int32)
        gang_id = np.full((p,), -1, np.int32)
        quota_id = np.full((p,), -1, np.int32)
        sel_id = np.full((p,), -1, np.int32)
        res_owner = np.full((p,), -1, np.int32)
        gpu_ratio = np.zeros((p,), np.float32)
        numa_single = np.zeros((p,), bool)
        daemonset = np.zeros((p,), bool)
        tol_id = np.zeros((p,), np.int32)
        valid = np.zeros((p,), bool)

        # (selector items, affinity expr key) -> (row, typed requirements)
        selectors: Dict[tuple, tuple] = {}
        # toleration set -> (row, typed list); row 0 = empty set
        tol_sets: Dict[tuple, tuple] = {(): (0, [])}
        # spread constraint key -> (row, constraint, namespace)
        spread_groups: Dict[tuple, tuple] = {}
        spread_row = np.full((p,), -1, np.int32)
        # inter-pod affinity: (ns, key, selector) -> (row, term, proto)
        anti_groups: Dict[tuple, tuple] = {}
        aff_groups: Dict[tuple, tuple] = {}
        anti_row = np.full((p,), -1, np.int32)
        aff_row = np.full((p,), -1, np.int32)
        anti_carried: List[tuple] = []  # (pod i, group row) per term
        aff_carried: List[tuple] = []
        spread_carried: List[tuple] = []  # (pod i, group row) per constraint
        for i, pod in enumerate(pods):
            requests[i] = resource_vec(pod.requests)
            estimated[i] = estimate_pod(pod, self.estimator_scaling,
                                        self.estimator_weights)
            qos[i] = int(pod.qos)
            prio_class[i] = int(pod.priority_class)
            prio[i] = pod.priority if pod.priority is not None else 0
            gang_id[i] = self.gang_index.get(pod.gang_name, -1)
            quota_id[i] = self.quota_index.get(pod.quota_name, -1)
            if pod.node_selector or pod.node_affinity:
                # the selector row covers BOTH the equality selector and
                # the required nodeAffinity expressions (ANDed, like the
                # upstream NodeAffinity filter folds them together)
                key = (frozenset(pod.node_selector.items()),
                       tuple((r.key, r.operator, tuple(r.values))
                             for r in pod.node_affinity))
                if key not in selectors and len(selectors) >= self.max_selectors:
                    raise ValueError(
                        f"distinct pod nodeSelectors exceed max_selectors="
                        f"{self.max_selectors}")
                if key not in selectors:
                    selectors[key] = (len(selectors),
                                      list(pod.node_affinity))
                sel_id[i] = selectors[key][0]
            for sel_key, group in ctx.reservation_owner_groups.items():
                if sel_key and _labels_match_key(pod.meta.labels, sel_key):
                    res_owner[i] = group
                    break
            gpu_ratio[i] = pod.gpu_memory_ratio
            numa_single[i] = pod.required_cpu_bind
            daemonset[i] = pod.is_daemonset
            if pod.tolerations:
                tkey = tuple(sorted((t.key, t.value, t.effect)
                                    for t in pod.tolerations))
                entry = tol_sets.get(tkey)
                if entry is None:
                    if len(tol_sets) >= self.max_tolerations:
                        raise ValueError(
                            f"distinct pod toleration sets exceed "
                            f"max_tolerations={self.max_tolerations}")
                    entry = (len(tol_sets), list(pod.tolerations))
                    tol_sets[tkey] = entry
                tol_id[i] = entry[0]
            # EVERY spread constraint is registered and gated (upstream
            # pods routinely carry zone + hostname together): hard
            # (DoNotSchedule) constraints gate by skew; ScheduleAnyway
            # constraints join as SOFT groups (skew = inf makes the gate
            # vacuous; the score penalty still prefers emptier domains,
            # upstream's scoring)
            degraded = False
            for c in pod.spread_constraints:
                # the group key includes the pod's own node constraints:
                # domain eligibility (which domains count toward the
                # skew minimum) follows the pods' reachable nodes
                # (upstream nodeAffinityPolicy=Honor), so pods with
                # different selectors must not share a group
                skey = (pod.meta.namespace, c.topology_key,
                        c.max_skew, c.when_unsatisfiable,
                        tuple(sorted(c.label_selector.items())),
                        tuple(sorted(pod.node_selector.items())),
                        tuple((r.key, r.operator, tuple(r.values))
                              for r in pod.node_affinity))
                entry = spread_groups.get(skey)
                if entry is None:
                    if len(spread_groups) >= self.max_spread_groups:
                        if spread_row[i] >= 0:
                            # an EXTRA constraint of one pod overflowing
                            # the group cap must not abort the whole
                            # batch: the pod degrades to unschedulable
                            # (never placed with an unmodeled
                            # constraint), everyone else schedules
                            degraded = True
                            break
                        raise ValueError(
                            f"distinct spread constraints exceed "
                            f"max_spread_groups={self.max_spread_groups}")
                    entry = (len(spread_groups), c, pod)
                    spread_groups[skey] = entry
                if spread_row[i] < 0:
                    spread_row[i] = entry[0]
                spread_carried.append((i, entry[0]))
            for term in pod.pod_affinity if not degraded else ():
                # EVERY carried term is registered, anti AND affinity —
                # the carrier matrices gate a pod by each term it
                # carries (multi-term pods). A pod already degraded by
                # spread overflow registers nothing: it will never be
                # placed, and its terms must neither consume scarce
                # group slots nor trip the cap into the abort path
                groups = anti_groups if term.anti else aff_groups
                rows = anti_row if term.anti else aff_row
                akey = (pod.meta.namespace, term.topology_key,
                        tuple(sorted(term.label_selector.items())))
                entry = groups.get(akey)
                if entry is None:
                    if len(groups) >= self.max_spread_groups:
                        if rows[i] >= 0:
                            # extra term over the cap: same degrade rule
                            degraded = True
                            break
                        raise ValueError(
                            f"distinct pod-affinity terms exceed "
                            f"max_spread_groups={self.max_spread_groups}")
                    entry = (len(groups), term, pod)
                    groups[akey] = entry
                if rows[i] < 0:
                    rows[i] = entry[0]
                if term.anti:
                    anti_carried.append((i, entry[0]))
                else:
                    aff_carried.append((i, entry[0]))
            valid[i] = not degraded

        # selector x node-label-group match matrix, padded to static
        # capacities so jitted programs never retrace across batches
        s = self.max_selectors
        l = self.max_label_groups
        sel_match = np.zeros((s, l), bool)
        for (sel_set, _), (si, reqs) in selectors.items():
            sel = dict(sel_set)
            for lab_key, li in ctx.node_label_groups.items():
                labels = dict(lab_key)
                sel_match[si, li] = (
                    all(labels.get(k) == v for k, v in sel.items())
                    and all(r.matches(labels) for r in reqs))
        # toleration x node-taint-group matrices (TaintToleration: the
        # filter forbids on any untolerated NoSchedule/NoExecute taint,
        # the score counts untolerated PreferNoSchedule taints). A fully
        # untainted, toleration-less batch collapses to [1, 1] so the
        # scheduler's taint gates compile out entirely.
        taints_modeled = not (len(ctx.node_taint_groups) == 1
                              and len(tol_sets) == 1)
        if not taints_modeled:
            tol_forbid = np.zeros((1, 1), bool)
            tol_prefer = np.zeros((1, 1), np.float32)
        else:
            tol_forbid = np.zeros((self.max_tolerations,
                                   self.max_taint_groups), bool)
            tol_prefer = np.zeros((self.max_tolerations,
                                   self.max_taint_groups), np.float32)
            for taint_key, gi in ctx.node_taint_groups.items():
                taints = [Taint(key=k, value=v, effect=e)
                          for (k, v, e) in taint_key]
                for _, (ti, tols) in tol_sets.items():
                    for taint in taints:
                        tolerated = any(t.tolerates(taint) for t in tols)
                        if tolerated:
                            continue
                        if taint.effect in ("NoSchedule", "NoExecute"):
                            tol_forbid[ti, gi] = True
                        elif taint.effect == "PreferNoSchedule":
                            tol_prefer[ti, gi] += 1.0
        # spread matrices: node domains per group + initial counts from
        # matching running AND assumed pods (every other capacity path —
        # requested, assigned_estimated, quota used — carries assumed
        # state; spread counts must too, or consecutive batches
        # undercount the domains they just filled)
        if not spread_groups:
            spread_max_skew = np.ones((1,), np.float32)
            spread_domain = np.full((1, 1), -1, np.int32)
            spread_count0 = np.zeros((1, 1), np.float32)
            spread_dvalid = np.zeros((1, 1), bool)
            spread_member = np.zeros((p, 1), bool)
            spread_carrier = np.zeros((p, 1), bool)
        else:
            # matrices sized to the ACTUAL group count, like
            # _affinity_matrices: the commit gates now loop per group,
            # so cap-padding would unroll dead [P, P] work per empty row
            sg_cap = len(spread_groups)
            d_cap = self.max_spread_domains
            spread_max_skew = np.ones((sg_cap,), np.float32)
            spread_domain = np.full((sg_cap, self.max_nodes), -1, np.int32)
            spread_count0 = np.zeros((sg_cap, d_cap), np.float32)
            spread_dvalid = np.zeros((sg_cap, d_cap), bool)
            spread_member = np.zeros((p, sg_cap), bool)
            spread_carrier = np.zeros((p, sg_cap), bool)
            for i, row in spread_carried:
                spread_carrier[i, row] = True
            for (row, c, proto) in spread_groups.values():
                ns = proto.meta.namespace
                # SOFT groups carry skew = inf: the device derives
                # softness from non-finite skew (never from dvalid — a
                # hard group whose domains are all unreachable must stay
                # hard)
                spread_max_skew[row] = (
                    float(c.max_skew)
                    if c.when_unsatisfiable == "DoNotSchedule"
                    else np.inf)
                self._fill_domain_map(c.topology_key, row, spread_domain)
                if c.when_unsatisfiable == "DoNotSchedule":
                    for ni, node in enumerate(self.nodes):
                        if node is None or spread_domain[row, ni] < 0:
                            continue
                        # a domain counts toward the skew minimum only
                        # when the group's pods can actually reach a node
                        # in it (upstream nodeAffinityPolicy=Honor:
                        # unreachable domains never pin the minimum)
                        reachable = (
                            all(node.meta.labels.get(k) == v
                                for k, v in proto.node_selector.items())
                            and all(r.matches(node.meta.labels)
                                    for r in proto.node_affinity))
                        if reachable:
                            spread_dvalid[row,
                                          spread_domain[row, ni]] = True
                # else: SOFT group — dvalid stays all-False, making the
                # skew gate vacuous (min over no domains = inf); only
                # the score preference applies
                self._count_matching(ns, c.label_selector, row,
                                     spread_domain, spread_count0)
                for i, pod in enumerate(pods):
                    spread_member[i, row] = self._matches(
                        pod, ns, c.label_selector)
        # existing pods' REQUIRED anti terms bind incoming pods too
        # (satisfyExistingPodsAntiAffinity): each such term becomes an
        # anti group whose carrier domain is forbidden; matching batch
        # pods without their own anti gate are gated by it. Only terms
        # RELEVANT to this batch (some batch pod matches the selector)
        # materialize — cluster-wide term diversity must neither exhaust
        # the group cap nor unroll dead work into the commit loop.
        carriers: List[tuple] = []
        irrelevant_terms: set = set()
        for ep, node_name in self._existing_pods():
            for term in ep.pod_affinity:
                if not term.anti:
                    continue
                akey = (ep.meta.namespace, term.topology_key,
                        tuple(sorted(term.label_selector.items())))
                if akey in irrelevant_terms:
                    continue
                entry = anti_groups.get(akey)
                if entry is None:
                    if not any(self._matches(pod, ep.meta.namespace,
                                             term.label_selector)
                               for pod in pods):
                        # memoized: thousands of carriers of one term
                        # must not rescan the batch per carrier
                        irrelevant_terms.add(akey)
                        continue
                    if len(anti_groups) >= self.max_spread_groups:
                        raise ValueError(
                            f"distinct pod-affinity terms exceed "
                            f"max_spread_groups={self.max_spread_groups}")
                    entry = (len(anti_groups), term, ep)
                    anti_groups[akey] = entry
                carriers.append((entry[0], node_name))
        anti_domain, anti_count0, anti_member = self._affinity_matrices(
            pods, anti_groups, p)
        # direction (b) surfaces: which pods CARRY each group's term, and
        # where existing carriers sit
        if not anti_groups:
            anti_carrier = np.zeros((p, 1), bool)
            anti_carrier_count0 = np.zeros((1, 1), np.float32)
        else:
            g_used = len(anti_groups)
            anti_carrier = np.zeros((p, g_used), bool)
            anti_carrier_count0 = np.zeros(
                (g_used, self.max_spread_domains), np.float32)
            for i, row in anti_carried:
                anti_carrier[i, row] = True
            for row, node_name in carriers:
                ni = self.node_index.get(node_name)
                if ni is not None and anti_domain[row, ni] >= 0:
                    anti_carrier_count0[row, anti_domain[row, ni]] += 1.0
        aff_domain, aff_count0, aff_member = self._affinity_matrices(
            pods, aff_groups, p)
        if not aff_groups:
            aff_carrier = np.zeros((p, 1), bool)
        else:
            aff_carrier = np.zeros((p, len(aff_groups)), bool)
            for i, row in aff_carried:
                aff_carrier[i, row] = True
        return PodBatch(
            requests=requests, estimated=estimated, qos=qos,
            priority_class=prio_class, priority=prio, gang_id=gang_id,
            quota_id=quota_id, selector_id=sel_id, selector_match=sel_match,
            reservation_owner=res_owner, gpu_ratio=gpu_ratio,
            numa_single=numa_single, daemonset=daemonset,
            toleration_id=tol_id, tol_forbid=tol_forbid,
            tol_prefer=tol_prefer,
            spread_id=spread_row, spread_carrier=spread_carrier,
            spread_member=spread_member,
            spread_max_skew=spread_max_skew,
            spread_domain=spread_domain, spread_count0=spread_count0,
            spread_dvalid=spread_dvalid,
            anti_id=anti_row, anti_member=anti_member,
            anti_carrier=anti_carrier,
            anti_domain=anti_domain, anti_count0=anti_count0,
            anti_carrier_count0=anti_carrier_count0,
            aff_id=aff_row, aff_carrier=aff_carrier,
            aff_member=aff_member,
            aff_domain=aff_domain, aff_count0=aff_count0, valid=valid,
            has_taints=taints_modeled,
            has_spread=bool(spread_groups),
            has_anti=bool(anti_groups),
            has_aff=bool(aff_groups))

    def _fill_domain_map(self, topology_key: str, row: int,
                         domain: np.ndarray) -> None:
        """Write each node's domain id for `topology_key` into
        domain[row] (-1 when the node lacks the label)."""
        domains: Dict[str, int] = {}
        for ni, node in enumerate(self.nodes):
            if node is None:
                continue
            val = node.meta.labels.get(topology_key)
            if val is None:
                continue
            if val not in domains:
                if len(domains) >= self.max_spread_domains:
                    raise ValueError(
                        f"distinct {topology_key!r} values exceed "
                        f"max_spread_domains={self.max_spread_domains}")
                domains[val] = len(domains)
            domain[row, ni] = domains[val]

    def _existing_pods(self):
        """(pod, node_name) for every running AND assumed pod — the set
        every count/constraint derived from cluster state must include."""
        return itertools.chain(
            ((rp, rp.node_name) for rp in self.running_pods),
            ((ap.pod, ap.node_name) for ap in self.assigned))

    @staticmethod
    def _matches(pod: Pod, ns: str, selector: Dict[str, str]) -> bool:
        return (pod.meta.namespace == ns
                and all(pod.meta.labels.get(k) == v
                        for k, v in selector.items()))

    def _count_matching(self, ns: str, selector: Dict[str, str], row: int,
                        domain: np.ndarray, count0: np.ndarray) -> None:
        """Count matching running+assumed pods into count0[row] per
        domain."""
        for cp, node_name in self._existing_pods():
            if not self._matches(cp, ns, selector):
                continue
            ni = self.node_index.get(node_name)
            if ni is not None and domain[row, ni] >= 0:
                count0[row, domain[row, ni]] += 1.0

    def _affinity_matrices(self, pods: Sequence[Pod],
                           groups: Dict[tuple, tuple], p: int):
        """(domain [G, N], count0 [G, D], member [P, G]) for inter-pod
        affinity groups; degenerate shapes when no group exists so the
        device gates compile out. `member[i, g]` marks batch pods that
        MATCH group g's selector — they charge its domain counts when
        placed whether or not they carry the term themselves (upstream
        counts all matching pods)."""
        if not groups:
            return (np.full((1, 1), -1, np.int32),
                    np.zeros((1, 1), np.float32),
                    np.zeros((p, 1), bool))
        # matrices sized to the ACTUAL group count — the device gates
        # loop over rows, so cap-padding would unroll dead [P, P] work
        # into the jitted commit loop
        g_used = len(groups)
        d_cap = self.max_spread_domains
        domain = np.full((g_used, self.max_nodes), -1, np.int32)
        count0 = np.zeros((g_used, d_cap), np.float32)
        member = np.zeros((p, g_used), bool)
        for (ns, _key, _sel), (row, term, proto) in groups.items():
            self._fill_domain_map(term.topology_key, row, domain)
            self._count_matching(ns, term.label_selector, row, domain,
                                 count0)
            for i, pod in enumerate(pods):
                member[i, row] = self._matches(pod, ns,
                                               term.label_selector)
        return domain, count0, member


def _selector_key(selector: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(selector.items()))


def _labels_match_key(labels: Dict[str, str], key: str) -> bool:
    if not key:
        return False
    for kv in key.split(","):
        k, _, v = kv.partition("=")
        if labels.get(k) != v:
            return False
    return True


@dataclasses.dataclass
class BuildContext:
    """Host-side lookup state shared between snapshot and pod-batch builds."""

    builder: SnapshotBuilder
    node_label_groups: Dict[frozenset, int]
    reservation_owner_groups: Dict[str, int]
    # node taint set (sorted (key, value, effect) tuples) -> taint group
    node_taint_groups: Dict[tuple, int] = dataclasses.field(
        default_factory=lambda: {(): 0})
