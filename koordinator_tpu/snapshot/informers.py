"""Informer/indexer plane: typed cluster-object caches with event
fan-out, incrementally maintained indexes, and the syncer that keeps the
device-resident snapshot fresh.

Capability parity with the reference's client/informer stack
(`pkg/client` generated informers + `frameworkext/informers.go` +
scheduler eventhandlers; SURVEY.md 2.7 and §7 hard part (e)): watch
events land in per-kind caches, handlers fan out, and the scheduler's
view stays fresh WITHIN the cycle budget — NodeMetric churn (the
dominant stream: every node re-reports each minute) flows as an O(K)
device-side delta ingest; node/device churn (scale-up/down) patches
node rows incrementally as an O(K) NodeTopologyDelta within the padded
capacity; only pod/quota/gang/reservation churn or capacity overflow
triggers the full columnar rebuild, the TPU analogue of the
reference's cache invalidation.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.snapshot.builder import SnapshotBuilder
from koordinator_tpu.snapshot.store import SnapshotStore
from koordinator_tpu.utils.sync import guarded_by

# event kinds (informer registry; frameworkext/informers.go)
KIND_NODE = "node"
KIND_POD = "pod"
KIND_NODE_METRIC = "node_metric"
KIND_RESERVATION = "reservation"
KIND_POD_GROUP = "pod_group"
KIND_QUOTA = "elastic_quota"
KIND_QUOTA_PROFILE = "quota_profile"
KIND_DEVICE = "device"

EVENT_ADD = "add"
EVENT_UPDATE = "update"
EVENT_DELETE = "delete"


@guarded_by(
    resource_version="_lock",
    _nodes="_lock",
    _pods="_lock",
    _metrics="_lock",
    _reservations="_lock",
    _pod_groups="_lock",
    _quotas="_lock",
    _quota_profiles="_lock",
    _devices="_lock",
    _pods_by_node="_lock",
    _pods_by_owner="_lock",
    _handlers="_lock",
    _assumed="_lock",
    _recent_assigned="_lock",
)
class ClusterInformerHub:
    """Typed caches + incremental indexes + subscriber fan-out. Also
    implements the manager's ClusterSource protocol so one hub feeds the
    control loop AND the snapshot syncer."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.resource_version = 0
        self._nodes: Dict[str, api.Node] = {}
        self._pods: Dict[str, api.Pod] = {}
        self._metrics: Dict[str, api.NodeMetric] = {}
        self._reservations: Dict[str, api.Reservation] = {}
        self._pod_groups: Dict[str, api.PodGroup] = {}
        self._quotas: Dict[str, api.ElasticQuota] = {}
        self._quota_profiles: Dict[str, api.ElasticQuotaProfile] = {}
        self._devices: Dict[str, api.Device] = {}
        # indexes (client-go Indexer analogue), maintained on every event
        self._pods_by_node: Dict[str, Dict[str, api.Pod]] = {}
        self._pods_by_owner: Dict[str, Dict[str, api.Pod]] = {}
        self._handlers: Dict[str, List[Callable[[str, object], None]]] = {}
        # assume cache (scheduler cache assume / podAssignCache): uid ->
        # (enriched pod, timestamp) for pods committed device-side whose
        # watch write-back has not arrived. Entries hold capacity in
        # every host recompute (rebuild + O(K) topology delta) and clear
        # when the watch delivers the bound pod, the pod is deleted, an
        # explicit forget returns the charge, or the assume TTL expires
        # (the k8s scheduler cache expires assumed pods the same way —
        # a lost bind must not leak phantom capacity forever).
        self._assumed: Dict[str, tuple] = {}
        # recently-assigned estimation window (podAssignCache,
        # load_aware.go:260-267): when the watch delivers the bound pod
        # the CAPACITY charge moves to the watched object, but the
        # NodeMetric will not reflect the pod for up to a report
        # interval — the estimation entry must survive the bind
        self._recent_assigned: Dict[str, tuple] = {}

    def subscribe(self, kind: str,
                  handler: Callable[[str, object], None]) -> None:
        with self._lock:
            self._handlers.setdefault(kind, []).append(handler)

    def _notify(self, kind: str, event: str, obj: object) -> None:
        self.resource_version += 1
        for h in self._handlers.get(kind, []):
            h(event, obj)

    # --- node -----------------------------------------------------------
    def upsert_node(self, node: api.Node) -> None:
        with self._lock:
            event = (EVENT_UPDATE if node.meta.name in self._nodes
                     else EVENT_ADD)
            self._nodes[node.meta.name] = node
            self._notify(KIND_NODE, event, node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            self._metrics.pop(name, None)
            self._devices.pop(name, None)
            if node is not None:
                self._notify(KIND_NODE, EVENT_DELETE, node)

    # --- pod ------------------------------------------------------------
    def upsert_pod(self, pod: api.Pod) -> None:
        with self._lock:
            uid = pod.meta.uid
            old = self._pods.get(uid)
            if old is not None:
                self._unindex_pod(old)
            self._pods[uid] = pod
            self._index_pod(pod)
            if pod.phase in ("Succeeded", "Failed"):
                self._assumed.pop(uid, None)
                self._recent_assigned.pop(uid, None)
            elif pod.node_name:
                # the watch caught up: the bound watched object now
                # carries the capacity charge; the estimation entry
                # survives into the recently-assigned window
                self._retire_assumed(uid)
            self._notify(KIND_POD,
                         EVENT_UPDATE if old is not None else EVENT_ADD,
                         pod)

    def delete_pod(self, uid: str) -> None:
        with self._lock:
            pod = self._pods.pop(uid, None)
            self._assumed.pop(uid, None)
            self._recent_assigned.pop(uid, None)
            if pod is not None:
                self._unindex_pod(pod)
                self._notify(KIND_POD, EVENT_DELETE, pod)

    def _retire_assumed(self, uid: str) -> None:
        """Capacity charge handed over (watched bound pod / reservation
        CR); keep the estimation entry for the report-interval window."""
        entry = self._assumed.pop(uid, None)
        if entry is not None:
            self._recent_assigned[uid] = entry

    # --- assume cache (scheduler_adapter.go assume/forget) --------------
    def note_assumed(self, pod: api.Pod,
                     timestamp: Optional[float] = None) -> None:
        """Record a device-side commit: `pod` must carry node_name and
        its fine-grained allocations (zone / GPU minors / aux instance /
        reservation) exactly as the commit charged them — the snapshot
        recomputes (rebuild and O(K) topology delta) mirror the charges
        from this record until the watch delivers the bound pod. Fires
        no event: the device snapshot already holds the charge; only
        future host recomputes need the record."""
        if not pod.node_name:
            raise ValueError("note_assumed: pod has no node_name")
        with self._lock:
            self._assumed[pod.meta.uid] = (
                pod, time.time() if timestamp is None else timestamp)

    def forget_assumed(self, uid: str) -> None:
        """Drop an assume record whose bind failed — pair with
        SnapshotStore.forget, which returns the device-side charges.
        The estimation entry goes too: a pod that never ran must not
        inflate the node's estimated usage."""
        with self._lock:
            self._assumed.pop(uid, None)
            self._recent_assigned.pop(uid, None)

    def expire_assumed(self, now: float, assume_ttl: float,
                       estimation_ttl: float) -> None:
        """TTL backstop (the k8s scheduler cache's assumed-pod expiry):
        an assume whose bind outcome never arrived is dropped after
        `assume_ttl` so a lost bind cannot leak phantom capacity
        forever; retired estimation entries age out after
        `estimation_ttl` (~ the NodeMetric report interval)."""
        with self._lock:
            for uid, (_, ts) in list(self._assumed.items()):
                if now - ts > assume_ttl:
                    del self._assumed[uid]
            for uid, (_, ts) in list(self._recent_assigned.items()):
                if now - ts > estimation_ttl:
                    del self._recent_assigned[uid]

    def assumed_entries(self) -> List[tuple]:
        """[(pod, timestamp)] of every capacity-holding assume."""
        with self._lock:
            return list(self._assumed.values())

    def estimation_entries(self) -> List[tuple]:
        """[(pod, timestamp)] feeding the recently-assigned usage
        estimation: outstanding assumes PLUS retired entries still in
        the report-interval window."""
        with self._lock:
            return (list(self._assumed.values())
                    + list(self._recent_assigned.values()))

    def _index_pod(self, pod: api.Pod) -> None:
        if pod.node_name:
            self._pods_by_node.setdefault(pod.node_name, {})[
                pod.meta.uid] = pod
        if pod.owner_workload:
            self._pods_by_owner.setdefault(pod.owner_workload, {})[
                pod.meta.uid] = pod

    def _unindex_pod(self, pod: api.Pod) -> None:
        for index, key in ((self._pods_by_node, pod.node_name),
                           (self._pods_by_owner, pod.owner_workload)):
            bucket = index.get(key)
            if bucket is not None:
                bucket.pop(pod.meta.uid, None)
                if not bucket:
                    del index[key]

    # --- the rest (one keyed-upsert shape) ------------------------------
    def _upsert(self, cache: Dict[str, object], key: str, kind: str,
                obj: object) -> None:
        with self._lock:
            event = EVENT_UPDATE if key in cache else EVENT_ADD
            cache[key] = obj
            self._notify(kind, event, obj)

    def set_node_metric(self, metric: api.NodeMetric) -> None:
        # even reading the cache BINDING belongs under the (reentrant)
        # lock: the guarded-by contract covers the attribute, and the
        # argument would otherwise be evaluated bare
        with self._lock:
            self._upsert(self._metrics, metric.node_name,
                         KIND_NODE_METRIC, metric)

    def upsert_reservation(self, r: api.Reservation) -> None:
        with self._lock:
            # consumers the CR's `allocated` now accounts for retire
            # from the assume cache — the hold must not be charged for
            # the same consumer twice (status.currentOwners)
            for uid in r.current_owners:
                self._retire_assumed(uid)
            self._upsert(self._reservations, r.meta.name,
                         KIND_RESERVATION, r)

    def delete_reservation(self, name: str) -> None:
        with self._lock:
            r = self._reservations.pop(name, None)
            if r is not None:
                self._notify(KIND_RESERVATION, EVENT_DELETE, r)

    def upsert_pod_group(self, pg: api.PodGroup) -> None:
        with self._lock:
            self._upsert(self._pod_groups, pg.meta.name, KIND_POD_GROUP,
                         pg)

    def upsert_quota(self, q: api.ElasticQuota) -> None:
        with self._lock:
            self._upsert(self._quotas, q.meta.name, KIND_QUOTA, q)

    def upsert_quota_profile(self, p: api.ElasticQuotaProfile) -> None:
        with self._lock:
            self._upsert(self._quota_profiles, p.meta.name,
                         KIND_QUOTA_PROFILE, p)

    def set_device(self, device: api.Device) -> None:
        with self._lock:
            self._upsert(self._devices, device.node_name, KIND_DEVICE,
                         device)

    # --- reads / indexes ------------------------------------------------
    def get_pod(self, uid: str) -> Optional[api.Pod]:
        with self._lock:
            return self._pods.get(uid)

    def pods_on_node(self, node_name: str) -> List[api.Pod]:
        with self._lock:
            return list(self._pods_by_node.get(node_name, {}).values())

    def pods_of_owner(self, owner: str) -> List[api.Pod]:
        with self._lock:
            return list(self._pods_by_owner.get(owner, {}).values())

    def reservations(self) -> List[api.Reservation]:
        with self._lock:
            return list(self._reservations.values())

    def get_reservation(self, name: str) -> Optional[api.Reservation]:
        with self._lock:
            return self._reservations.get(name)

    def read_all(self) -> Dict[str, object]:
        """One CONSISTENT copy of every cache under a single lock window
        — the rebuild path must not stitch a snapshot from reads taken
        at different versions (a pod observed without its node would be
        silently dropped by the builder)."""
        with self._lock:
            return {
                "nodes": list(self._nodes.values()),
                "metrics": dict(self._metrics),
                "pods_by_node": {n: list(b.values())
                                 for n, b in self._pods_by_node.items()},
                "quotas": list(self._quotas.values()),
                "pod_groups": list(self._pod_groups.values()),
                "reservations": list(self._reservations.values()),
                "devices": list(self._devices.values()),
                "assumed": list(self._assumed.values()),
                "recent_assigned": list(self._recent_assigned.values()),
                "resource_version": self.resource_version,
            }

    def get_node(self, name: str) -> Optional[api.Node]:
        with self._lock:
            return self._nodes.get(name)

    def get_device(self, node_name: str) -> Optional[api.Device]:
        with self._lock:
            return self._devices.get(node_name)

    def devices_by_node(self) -> Dict[str, api.Device]:
        """node name -> Device CR (the mapping the preemption post
        filter's get_devices provider wants)."""
        with self._lock:
            return dict(self._devices)

    # --- ClusterSource protocol (cmd/manager.py) ------------------------
    def nodes(self) -> List[api.Node]:
        with self._lock:
            return list(self._nodes.values())

    def node_metrics(self) -> Dict[str, api.NodeMetric]:
        with self._lock:
            return dict(self._metrics)

    def pods_by_node(self) -> Dict[str, List[api.Pod]]:
        with self._lock:
            return {n: list(b.values())
                    for n, b in self._pods_by_node.items()}

    def capacity_pods_by_node(self) -> Dict[str, List[api.Pod]]:
        """pods_by_node MERGED with the assume cache — the surviving-
        capacity view the preemption dry run must evaluate (assumed
        pods hold capacity exactly like bound ones; the scheduler
        cache's merged NodeInfo view)."""
        with self._lock:
            out = {n: list(b.values())
                   for n, b in self._pods_by_node.items()}
            seen = {uid for b in self._pods_by_node.values() for uid in b}
            for uid, (pod, _) in self._assumed.items():
                if uid not in seen and pod.node_name:
                    out.setdefault(pod.node_name, []).append(pod)
            return out

    def quota_profiles(self) -> List[api.ElasticQuotaProfile]:
        with self._lock:
            return list(self._quota_profiles.values())


def _node_identity(node: api.Node) -> tuple:
    """Hashable fingerprint of every node field that flows into a
    snapshot row (labels, annotations, allocatable, taints,
    schedulability, NUMA topology). Real clusters heartbeat node STATUS
    every sync window; without this filter each heartbeat dirties the
    node and >delta_pad heartbeats collapse the O(K) topology path into
    the full rebuild it exists to avoid (the reference informers filter
    updates the same way)."""
    topo = node.topology
    tfp = None
    if topo is not None:
        tfp = (topo.policy, topo.cpus_per_core,
               topo.kubelet_reserved_cpuset, topo.ls_share_pool,
               topo.be_share_pool,
               tuple((z.cpus_milli, z.memory_mib, z.cpuset)
                     for z in topo.zones))
    return (tuple(sorted(node.meta.labels.items())),
            tuple(sorted(node.meta.annotations.items())),
            tuple(sorted((str(k), float(v))
                         for k, v in node.allocatable.items())),
            tuple((t.key, t.value, t.effect) for t in node.taints),
            node.unschedulable, tfp)


@guarded_by(
    _full_dirty="_lock",
    _dirty_metrics="_lock",
    _dirty_topology="_lock",
    _node_seen="_lock",
    # builder/ctx mutate only inside the attached service's commit
    # critical section (sync()/build_pod_batch take _commit_guard());
    # _view_lock ADDITIONALLY pairs the (snapshot, builder) swap for
    # cross-thread summary readers — lock order commit -> view
    builder="external:SchedulerService._commit_lock",
    ctx="external:SchedulerService._commit_lock",
    # sync() runs on one loop; these tallies are observability reads
    # elsewhere — torn reads tolerated by design
    full_rebuilds="racy-monitor",
    delta_ingests="racy-monitor",
    topology_ingests="racy-monitor",
    # wired once by attach_scheduler before concurrent traffic starts
    _service="publish-once",
    hub="publish-once",
    store="publish-once",
    max_nodes="publish-once",
    delta_pad="publish-once",
    now_fn="publish-once",
    assume_ttl="publish-once",
    estimation_ttl="publish-once",
    builder_caps="publish-once",
)
class SnapshotSyncer:
    """Keeps a SnapshotStore fresh from a hub: NodeMetric churn becomes
    an O(K) device-side delta (store.ingest), anything that changes the
    snapshot's SHAPE (nodes, running pods, quotas, gangs, reservations,
    devices) schedules a full columnar rebuild on the next sync."""

    def __init__(self, hub: ClusterInformerHub, store: SnapshotStore,
                 max_nodes: int, delta_pad: int = 64,
                 now_fn: Callable[[], float] = time.time,
                 assume_ttl_seconds: float = 900.0,
                 estimation_ttl_seconds: float = 180.0,
                 **builder_caps):
        self.hub = hub
        self.store = store
        self.max_nodes = max_nodes
        self.delta_pad = delta_pad
        self.now_fn = now_fn
        # assume expiry backstop (k8s assumed-pod TTL: a bind whose
        # outcome never arrives must not leak capacity forever) and the
        # recently-assigned estimation window (~NodeMetric report
        # interval + slack)
        self.assume_ttl = assume_ttl_seconds
        self.estimation_ttl = estimation_ttl_seconds
        # set by attach_scheduler: snapshot publishes/ingests serialize
        # with the service's batch commits (lost-update + assume-hook
        # TOCTOU guard); lock order is commit -> view, everywhere
        self._service = None
        self.builder_caps = builder_caps
        self.builder: Optional[SnapshotBuilder] = None
        self.ctx = None
        self._full_dirty = True
        self._dirty_metrics: set = set()
        self._lock = threading.Lock()
        # guards the (store snapshot, builder indexes) pair for readers
        # on other threads (the ServicesServer summary providers)
        self._view_lock = threading.Lock()
        self.full_rebuilds = 0
        self.delta_ingests = 0
        self.topology_ingests = 0
        self._dirty_topology: set = set()
        # last ingested identity fingerprint per node (heartbeat filter)
        self._node_seen: Dict[str, tuple] = {}
        for kind in (KIND_POD, KIND_RESERVATION, KIND_POD_GROUP,
                     KIND_QUOTA):
            hub.subscribe(kind, self._on_shape_event)
        # node add/remove/update and Device CR churn patch node rows
        # incrementally (NodeTopologyDelta) — the reference's informers
        # absorb node churn without cache invalidation too
        hub.subscribe(KIND_NODE, self._on_node_event)
        hub.subscribe(KIND_DEVICE, self._on_device_event)
        hub.subscribe(KIND_NODE_METRIC, self._on_metric_event)

    def _on_shape_event(self, event: str, obj: object) -> None:
        with self._lock:
            self._full_dirty = True

    def _on_node_event(self, event: str, obj) -> None:
        name = obj.meta.name
        fp = None if event == EVENT_DELETE else _node_identity(obj)
        with self._lock:
            if fp is not None and self._node_seen.get(name) == fp:
                return  # pure status heartbeat — identity unchanged
            if fp is None:
                self._node_seen.pop(name, None)
            else:
                self._node_seen[name] = fp
            self._dirty_topology.add(name)

    def _on_device_event(self, event: str, obj) -> None:
        with self._lock:
            self._dirty_topology.add(obj.node_name)

    def _on_metric_event(self, event: str, obj) -> None:
        with self._lock:
            self._dirty_metrics.add(obj.node_name)

    def sync(self, now: Optional[float] = None) -> str:
        """One sync pass; returns "full" | "topology" | "delta" | "noop".

        Precedence: anything that invalidates non-node state rebuilds;
        pure node/device churn within one delta's capacity patches the
        node rows device-side (O(K)); metric churn is the O(K) metric
        delta. Overflow or capacity pressure (rows, label/taint groups,
        PCIe ids) falls back to the rebuild — never silent truncation."""
        now = self.now_fn() if now is None else now
        self.hub.expire_assumed(now, self.assume_ttl, self.estimation_ttl)
        with self._lock:
            full = self._full_dirty
            topo = sorted(self._dirty_topology)
            dirty = sorted(self._dirty_metrics)
            self._full_dirty = False
            self._dirty_topology.clear()
            self._dirty_metrics.clear()
        # serialize the whole apply phase with in-flight batch commits
        # when a scheduler is attached: an unserialized rebuild landing
        # between a batch's snapshot read and its post-commit publish
        # would be silently overwritten, and the assume hook would
        # resolve result rows against a swapped builder
        with self._commit_guard():
            return self._sync_locked(full, topo, dirty, now)

    def _commit_guard(self):
        import contextlib

        if self._service is None:
            return contextlib.nullcontext()
        return self._service.commit_guard()

    def _sync_locked(self, full: bool, topo: List[str],
                     dirty: List[str], now: float) -> str:
        if full or (topo and self.builder is None):
            self._rebuild(now)
            return "full"
        if topo:
            if len(topo) > self.delta_pad:
                self._rebuild(now)
                return "full"
            metrics = self.hub.node_metrics()
            try:
                # refresh the assume-cache mirror FIRST: the delta
                # recomputes each touched row from the builder's host
                # view, and a row recompute that missed an in-flight
                # assume would erase its device-side commit charges
                # (ADVICE r4 medium)
                self.builder.set_assumed_pods(
                    self.hub.assumed_entries(),
                    self.hub.estimation_entries())
                # under the view lock: the summary providers iterate
                # builder.node_index against store.current() — the
                # index mutation and the ingest must land as one unit,
                # exactly like _rebuild's (snapshot, builder) swap
                with self._view_lock:
                    # removals FIRST: a same-window replacement at full
                    # row capacity must free the row before the add
                    # claims it (otherwise a spurious capacity error
                    # forfeits the O(K) path)
                    resolved = [(name, self.hub.get_node(name))
                                for name in topo]
                    for name, node in resolved:
                        if node is None and \
                                name in self.builder.node_index:
                            self.builder.remove_node(name)
                    for name, node in resolved:
                        if node is None:
                            continue
                        self.builder.add_node(node)
                        device = self.hub.get_device(name)
                        if device is not None:
                            self.builder.devices[name] = device
                        metric = metrics.get(name)
                        if metric is not None:
                            self.builder.set_node_metric(metric)
                    delta = self.builder.topology_delta(
                        topo, now=now, pad_to=self.delta_pad)
                    self.store.ingest(delta)
            except ValueError:
                # capacity pressure (rows / label groups / taint groups
                # / minors): the rebuild re-buckets
                self._rebuild(now)
                return "full"
            self.topology_ingests += 1
            # metric churn for OTHER nodes still applies below (the
            # topology rows already carried their own metric columns)
            dirty = [d for d in dirty if d not in set(topo)]
        if dirty:
            if len(dirty) > self.delta_pad:
                # more churn than one delta's capacity: a rebuild is the
                # O(N) fallback, never silent truncation
                self._rebuild(now)
                return "full"
            assert self.builder is not None
            metrics = self.hub.node_metrics()
            # the metric rows' assigned-estimation columns recompute
            # from the assume-cache mirror — keep it fresh here too
            self.builder.set_assumed_pods(self.hub.assumed_entries(),
                                          self.hub.estimation_entries())
            for name in dirty:
                metric = metrics.get(name)
                if metric is not None:
                    self.builder.set_node_metric(metric)
            self.store.ingest(self.builder.metric_delta(
                dirty, now=now, pad_to=self.delta_pad))
            self.delta_ingests += 1
            return "topology" if topo else "delta"
        return "topology" if topo else "noop"

    def attach_scheduler(self, service) -> None:
        """Wire the service's post-commit hook into the hub's assume
        cache: every placed pod is recorded host-side with the fine-
        grained allocations the device commit actually charged (zone /
        GPU minors / aux instance / reservation slot), so subsequent
        rebuilds and O(K) topology deltas recompute rows WITH the
        in-flight charges (the reference's scheduler cache assume +
        podAssignCache, scheduler_adapter.go; ADVICE r4: a routine node
        heartbeat must not erase commit charges). Callers that forget a
        failed bind via store.forget must also hub.forget_assumed.

        Also serializes this syncer's publishes/ingests with the
        service's batch commits (sync() takes service.commit_guard());
        the service invokes the hook under the same lock, so result
        rows always resolve against the builder generation the batch
        actually scheduled on."""
        service.on_assumed = self._record_assumes
        self._service = service
        # chain the gang-failure tier: a strict gang PROVEN short
        # releases its earlier-assumed members' host records immediately
        # (the device-side charges return through the embedding's
        # store.forget tier / the Permit wait-expiry backstop; the
        # assume TTL is the final host backstop)
        prev_gang_failed = service.on_gang_failed

        def _on_gang_failed(gids, result):
            self._forget_failed_gang_assumes(gids)
            if prev_gang_failed is not None:
                prev_gang_failed(gids, result)

        service.on_gang_failed = _on_gang_failed

    def _forget_failed_gang_assumes(self, gang_indices) -> None:
        with self._view_lock:
            if self.builder is None:
                return
            names = {self.builder.gangs[int(g)].meta.name
                     for g in gang_indices
                     if 0 <= int(g) < len(self.builder.gangs)}
        if not names:
            return
        for pod, _ in self.hub.assumed_entries():
            if pod.gang_name in names:
                self.hub.forget_assumed(pod.meta.uid)

    def _record_assumes(self, assignment, typed_pods, result) -> None:
        import dataclasses as _dc

        from koordinator_tpu.snapshot.schema import AUX_FPGA, AUX_RDMA

        now = self.now_fn()
        with self._view_lock:
            if self.builder is None:
                return
            row_name = {i: n for n, i in self.builder.node_index.items()}
            res_names = [r.meta.name for r in self.builder.reservations]
        assignment = np.asarray(assignment)
        numa_zone = np.asarray(result.numa_zone)
        gpu_take = np.asarray(result.gpu_take)
        aux_inst = np.asarray(result.aux_inst)
        res_slot = np.asarray(result.res_slot)
        for i, pod in enumerate(typed_pods):
            if pod is None or i >= assignment.shape[0]:
                continue
            ni = int(assignment[i])
            if ni < 0:
                continue
            name = row_name.get(ni)
            if name is None:
                continue
            minors = ()
            if gpu_take.ndim == 2 and gpu_take.shape[1]:
                minors = tuple(int(m) for m in np.nonzero(gpu_take[i])[0])
            rdma = fpga = -1
            if aux_inst.ndim == 2 and aux_inst.shape[1] > max(AUX_RDMA,
                                                              AUX_FPGA):
                rdma = int(aux_inst[i, AUX_RDMA])
                fpga = int(aux_inst[i, AUX_FPGA])
            slot = int(res_slot[i]) if res_slot.size else -1
            # NOTE: multi-zone best-effort NUMA takes are mirrored to the
            # single reported zone (result.numa_zone) — the exact split
            # lives only in the device commit until the watch delivers
            # the bound pod's resource-status annotation
            self.hub.note_assumed(_dc.replace(
                pod, node_name=name,
                allocated_numa_zone=(int(numa_zone[i])
                                     if numa_zone.size else -1),
                allocated_gpu_minors=minors,
                allocated_rdma_inst=rdma,
                allocated_fpga_inst=fpga,
                reservation_name=(res_names[slot]
                                  if 0 <= slot < len(res_names)
                                  else pod.reservation_name),
            ), timestamp=now)

    def build_pod_batch(self, pods, max_pods: Optional[int] = None):
        """Build a PodBatch against the CURRENT builder with a FRESH
        assume-cache mirror. This is the structural home of the
        cross-batch count contract (core.py charge_domain_counts): the
        topology count0 surfaces recompute from running + assumed pods,
        so a batch built here sees every earlier schedule() call's
        placements in its spread/anti/affinity counts even when no sync
        ran in between (the bench threads counts explicitly through the
        scan carry; the service path threads them through here)."""
        self.hub.expire_assumed(self.now_fn(), self.assume_ttl,
                                self.estimation_ttl)
        # commit guard FIRST (the one lock order: commit -> view): the
        # mirror swap must not race a sync() or an in-flight schedule
        # commit whose assume hook has not recorded yet
        with self._commit_guard():
            with self._view_lock:
                if self.builder is None:
                    raise RuntimeError(
                        "build_pod_batch before first sync()")
                self.builder.set_assumed_pods(
                    self.hub.assumed_entries(),
                    self.hub.estimation_entries())
                return self.builder.build_pod_batch(pods, self.ctx,
                                                    max_pods=max_pods)

    def register_preemption(self, service, on_nominate) -> None:
        """Register the default-preemption PostFilter on the service's
        error chain with HUB-backed providers. devices_by_node is wired
        BY DEFAULT (VERDICT r4 #5: the per-instance GPU/aux recheck
        narrowing must apply only when no Device CRs exist, not
        whenever a caller forgets the optional argument), and the pod
        view includes assume-cache entries so the dry run sees
        in-flight capacity."""
        from koordinator_tpu.scheduler.errorhandler import (
            make_preemption_post_filter,
        )
        service.error_dispatcher.register(post=make_preemption_post_filter(
            self.hub.nodes, self.hub.capacity_pods_by_node, on_nominate,
            get_devices=self.hub.devices_by_node))

    def register_services(self, registry) -> None:
        """Register the syncer-backed service payloads on a frameworkext
        ServiceRegistry — the production wiring for the
        /apis/v1/plugins/{elasticquota,deviceshare} endpoints (embedded
        deployments compose hub + syncer + SchedulerService in one
        process; the sidecar edge serves its own summaries)."""
        registry.register("elasticquota", self.quota_summary)
        registry.register("deviceshare", self.device_summary)

    def quota_summary(self) -> dict:
        """The elastic-quota service payload (frameworkext services.go
        quota summaries): per quota name, min / used / runtime from the
        CURRENT device snapshot. Empty before the first sync."""
        with self._view_lock:
            if self.builder is None:
                return {}
            snap = self.store.current()
            # COPY the index inside the lock: the incremental topology
            # path mutates the live builder dicts in place (no swap,
            # unlike _rebuild), so iterating them after release races
            # a concurrent sync
            quota_index = dict(self.builder.quota_index)
        used = np.asarray(snap.quotas.used)
        runtime = np.asarray(snap.quotas.runtime)
        qmin = np.asarray(snap.quotas.min)
        out = {}
        for name, qi in quota_index.items():
            out[name] = {
                "min": [float(v) for v in qmin[qi]],
                "used": [float(v) for v in used[qi]],
                "runtime": [None if not np.isfinite(v) else float(v)
                            for v in runtime[qi]],
            }
        return out

    def device_summary(self) -> dict:
        """The deviceshare service payload: per node, the aggregate GPU
        capacity (per-instance totals x instance count) and each
        instance's remaining free."""
        from koordinator_tpu.snapshot.schema import DEV_CORE, DEV_MEM

        with self._view_lock:
            if self.builder is None:
                return {}
            snap = self.store.current()
            # copy inside the lock — see quota_summary
            node_index = dict(self.builder.node_index)
        gpu_free = np.asarray(snap.devices.gpu_free)
        gpu_total = np.asarray(snap.devices.gpu_total)
        gpu_valid = np.asarray(snap.devices.gpu_valid)
        out = {}
        for name, ni in node_index.items():
            count = int(gpu_valid[ni].sum())
            if count == 0:
                continue
            out[name] = {
                "gpuTotal": {
                    "count": count,
                    "core": float(gpu_total[ni, DEV_CORE]) * count,
                    "memoryMiB": float(gpu_total[ni, DEV_MEM]) * count},
                "instances": [
                    {"minor": int(m),
                     "coreFree": float(gpu_free[ni, m, DEV_CORE]),
                     "memoryFreeMiB": float(gpu_free[ni, m, DEV_MEM])}
                    for m in np.nonzero(gpu_valid[ni])[0]],
            }
        return out

    def _rebuild(self, now: float) -> None:
        state = self.hub.read_all()  # one consistent version
        b = SnapshotBuilder(max_nodes=self.max_nodes, **self.builder_caps)
        for node in state["nodes"]:
            b.add_node(node)
        for metric in state["metrics"].values():
            b.set_node_metric(metric)
        gang_held: Dict[str, int] = {}
        for pods in state["pods_by_node"].values():
            for pod in pods:
                # every bound non-terminal pod holds capacity (upstream
                # NodeInfo semantics): a bound-but-not-yet-running pod
                # must keep the charge its assume entry held before the
                # watch delivered it
                if pod.phase not in ("Succeeded", "Failed"):
                    b.add_running_pod(pod)
                    if pod.gang_name:
                        gang_held[pod.gang_name] = \
                            gang_held.get(pod.gang_name, 0) + 1
        b.set_assumed_pods(state["assumed"],
                           state["assumed"] + state["recent_assigned"])
        bound_uids = {p.meta.uid for p in b.running_pods}
        for pod, _ in state["assumed"]:
            if pod.gang_name and pod.meta.uid not in bound_uids:
                gang_held[pod.gang_name] = \
                    gang_held.get(pod.gang_name, 0) + 1
        for q in state["quotas"]:
            b.add_quota(q)
        for pg in state["pod_groups"]:
            # bound + assumed members count toward quorum (GangState
            # .assumed is "members already assumed/bound"; a rebuild
            # must not forget a gang's held members)
            b.add_gang(pg, assumed=gang_held.get(pg.meta.name, 0))
        for r in state["reservations"]:
            b.add_reservation(r)
        for d in state["devices"]:
            b.add_device(d)
        snap, ctx = b.build(now=now)
        # the (snapshot, builder) PAIR swaps atomically under the view
        # lock: a summary request racing the swap must never index the
        # new arrays with the old builder's name->row mapping
        with self._view_lock:
            self.store.publish(snap)
            self.builder, self.ctx = b, ctx
        self.full_rebuilds += 1
