"""koord-manager webhook equivalents: pod mutation/validation by
ClusterColocationProfile and the ElasticQuota topology guard
(SURVEY.md 2.3, pkg/webhook)."""

from koordinator_tpu.webhook.pod_mutating import PodMutator  # noqa: F401
from koordinator_tpu.webhook.pod_validating import validate_pod  # noqa: F401
from koordinator_tpu.webhook.node_webhook import (  # noqa: F401
    NodeMutator,
    validate_node,
)
from koordinator_tpu.webhook.config_validating import (  # noqa: F401
    validate_slo_configmap,
)
from koordinator_tpu.webhook.elasticquota import (  # noqa: F401
    DEFAULT_QUOTA_NAME,
    ROOT_QUOTA_NAME,
    SYSTEM_QUOTA_NAME,
    QuotaTopology,
)
