"""ClusterColocationProfile pod mutation.

Behavior parity with pkg/webhook/pod/mutating/cluster_colocation_profile.go
(SURVEY.md 2.3):
- On CREATE, every profile whose namespaceSelector matches the pod's
  namespace labels AND whose selector matches the pod's labels applies, in
  list order (:53-110); a probability percent gates each profile (:147-157
  shouldSkipProfile).
- A matching profile stamps labels/annotations (incl. key remappings),
  schedulerName, the QoS label, the k8s priorityClassName + resolved
  priority value, and the koordinator priority label (:159-236).
- Afterwards (unless skipped), non-Prod pods get their cpu/memory
  requests/limits TRANSLATED to the priority tier's extended resources —
  batch-cpu/batch-memory for Batch, mid-* for Mid — erasing the native
  entries (mutatePodResourceSpec :239-294, replaceAndEraseResource); a
  translated limit without a request gets request=limit
  (restrictResourceRequestAndLimit :281-294).

Requests/limits here are pod-level aggregates (ResourceKind-keyed), the
granularity the rest of this framework schedules at.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Mapping, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_EXTENDED_RESOURCE_SPEC,
    PriorityClass,
    ResourceKind,
    encode_extended_resource_spec,
    priority_class_of,
    selector_matches,
    translate_resource_by_priority,
)


class PodMutator:
    """The mutating admission path for pods.

    - `namespaces`: namespace name -> labels (the Namespace objects the
      reference fetches per request).
    - `priority_classes`: k8s PriorityClass name -> value.
    - `rng`: percent roll for probability gating (inject for tests).
    """

    def __init__(self, profiles: Sequence[api.ClusterColocationProfile] = (),
                 namespaces: Optional[Mapping[str, Dict[str, str]]] = None,
                 priority_classes: Optional[Mapping[str, int]] = None,
                 rng: Callable[[], float] = random.random,
                 skip_mutating_resources: bool = False):
        self.profiles = list(profiles)
        self.namespaces = dict(namespaces or {})
        self.priority_classes = dict(priority_classes or {})
        self.rng = rng
        self.skip_mutating_resources = skip_mutating_resources

    def mutate(self, pod: api.Pod, operation: str = "Create") -> bool:
        """Apply matching profiles in place; returns whether anything
        changed. Only CREATE is mutated (:54-56)."""
        if operation != "Create":
            return False
        matched = [p for p in self.profiles if self._matches(p, pod)]
        if not matched:
            return False
        changed = False
        skip_resources = self.skip_mutating_resources
        for profile in matched:
            # the skip flag latches BEFORE the probability roll, exactly as
            # the reference does (cluster_colocation_profile.go:88-99) — a
            # skip-resources profile suppresses translation even for the
            # fraction of pods its probability gate passes over
            if profile.skip_update_resources:
                skip_resources = True
            if self._skip_by_probability(profile):
                continue
            changed |= self._apply(profile, pod)
        if not skip_resources:
            changed |= self._mutate_resource_spec(pod)
        return changed

    # -- matching ------------------------------------------------------------

    def _matches(self, profile: api.ClusterColocationProfile,
                 pod: api.Pod) -> bool:
        ns_labels = self.namespaces.get(pod.meta.namespace, {})
        if not selector_matches(profile.namespace_selector, ns_labels):
            return False
        return selector_matches(profile.selector, pod.meta.labels)

    def _skip_by_probability(self,
                             profile: api.ClusterColocationProfile) -> bool:
        percent = profile.probability * 100.0
        return percent == 0 or (percent != 100.0
                                and self.rng() * 100.0 > percent)

    # -- application ---------------------------------------------------------

    def _apply(self, profile: api.ClusterColocationProfile,
               pod: api.Pod) -> bool:
        changed = False
        for k, v in profile.labels.items():
            if pod.meta.labels.get(k) != v:
                pod.meta.labels[k] = v
                changed = True
        for k, v in profile.annotations.items():
            if pod.meta.annotations.get(k) != v:
                pod.meta.annotations[k] = v
                changed = True
        for old, new in profile.label_keys_mapping.items():
            if old in pod.meta.labels and \
                    pod.meta.labels.get(new) != pod.meta.labels[old]:
                pod.meta.labels[new] = pod.meta.labels[old]
                changed = True
        for old, new in profile.annotation_keys_mapping.items():
            if old in pod.meta.annotations and \
                    pod.meta.annotations.get(new) != pod.meta.annotations[old]:
                pod.meta.annotations[new] = pod.meta.annotations[old]
                changed = True
        if profile.scheduler_name:
            pod.scheduler_name = profile.scheduler_name
            changed = True
        if profile.qos_class:
            pod.qos_label = profile.qos_class
            changed = True
        if profile.priority_class_name:
            value = self.priority_classes.get(profile.priority_class_name)
            if value is None:
                raise KeyError(
                    f"PriorityClass {profile.priority_class_name!r} not found")
            pod.priority_class_name = profile.priority_class_name
            pod.priority = value
            changed = True
        if profile.koordinator_priority is not None:
            from koordinator_tpu.api.extension import LABEL_POD_PRIORITY
            pod.meta.labels[LABEL_POD_PRIORITY] = str(
                profile.koordinator_priority)
            changed = True
        return changed

    # -- resource translation ------------------------------------------------

    def _mutate_resource_spec(self, pod: api.Pod) -> bool:
        pc = priority_class_of(pod.priority, pod.priority_class_label,
                               pod.priority_class_name)
        if pc in (PriorityClass.NONE, PriorityClass.PROD):
            return False
        changed = False
        for rl in (pod.requests, pod.limits):
            for kind in (ResourceKind.CPU, ResourceKind.MEMORY):
                target = translate_resource_by_priority(kind, pc)
                if target is kind:
                    continue
                if kind in rl:
                    rl[target] = rl.pop(kind)
                    changed = True
        # a translated limit without a request gets request=limit
        for kind in (ResourceKind.CPU, ResourceKind.MEMORY):
            target = translate_resource_by_priority(kind, pc)
            if target is kind:
                continue
            if target in pod.limits and target not in pod.requests:
                pod.requests[target] = pod.limits[target]
                changed = True
        # the runtime-facing copy of the translated tiers: NRI/proxy
        # contexts have no pod spec, only annotations
        # (container_context.go:93-120 reads this back). Written whenever
        # the spec holds extended kinds — even if the submitter already
        # translated them (changed=False), matching mutateByExtendedResources
        # (extended_resource_spec.go) which dumps the annotation
        # unconditionally from the final spec.
        spec = encode_extended_resource_spec(pod.requests, pod.limits)
        if spec and pod.meta.annotations.get(
                ANNOTATION_EXTENDED_RESOURCE_SPEC) != spec:
            pod.meta.annotations[ANNOTATION_EXTENDED_RESOURCE_SPEC] = spec
            changed = True
        return changed
