"""Admission dispatch framework: one entry point routing every object
kind through its gated mutating + validating handlers.

Capability parity with `pkg/webhook/server.go` + `add_pod.go`/
`add_node.go`/`add_configmap.go`/`add_quota.go`: the reference registers
per-kind handlers on a webhook server behind the WebhookFramework /
PodMutatingWebhook / PodValidatingWebhook feature gates; here the edge
calls `AdmissionDispatcher.admit` with typed objects and gets back the
combined decision (mutating runs first, then validating — the k8s
admission phase order)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from koordinator_tpu.api import types as api
from koordinator_tpu.features import FeatureGate, new_default_gate
from koordinator_tpu.webhook.config_validating import validate_slo_configmap
from koordinator_tpu.webhook.elasticquota import QuotaTopology
from koordinator_tpu.webhook.node_webhook import (
    AdmissionError,
    NodeMutator,
    validate_node,
)
from koordinator_tpu.webhook.pod_mutating import PodMutator
from koordinator_tpu.webhook.pod_validating import validate_pod

KIND_POD = "Pod"
KIND_NODE = "Node"
KIND_CONFIGMAP = "ConfigMap"
KIND_ELASTIC_QUOTA = "ElasticQuota"


@dataclasses.dataclass
class AdmissionResponse:
    allowed: bool = True
    mutated: bool = False
    errors: List[str] = dataclasses.field(default_factory=list)


class AdmissionDispatcher:
    """Routes (kind, operation, object) through the gated handlers."""

    def __init__(self, mutator: Optional[PodMutator] = None,
                 quota_topology: Optional[QuotaTopology] = None,
                 gate: Optional[FeatureGate] = None):
        self.mutator = mutator
        self.node_mutator = NodeMutator()
        self.quota_topology = quota_topology
        self.gate = gate or new_default_gate()

    def admit(self, kind: str, obj, operation: str = "Create",
              old=None) -> AdmissionResponse:
        resp = AdmissionResponse()
        if not self.gate.enabled("WebhookFramework"):
            return resp  # framework off: everything passes untouched
        handler = {
            KIND_POD: self._admit_pod,
            KIND_NODE: self._admit_node,
            KIND_CONFIGMAP: self._admit_configmap,
            KIND_ELASTIC_QUOTA: self._admit_quota,
        }.get(kind)
        if handler is None:
            return resp  # unregistered kinds pass through
        if operation == "Delete" and kind != KIND_ELASTIC_QUOTA:
            # only the quota guard vets deletion (children/pods checks);
            # validating a doomed object would let a pre-existing invalid
            # one become undeletable
            return resp
        handler(resp, obj, operation, old)
        return resp

    def _admit_pod(self, resp: AdmissionResponse, pod: api.Pod,
                   operation: str, _old) -> None:
        if self.mutator is not None and \
                self.gate.enabled("PodMutatingWebhook"):
            try:
                resp.mutated = self.mutator.mutate(pod, operation)
            except (ValueError, KeyError) as e:
                resp.allowed = False
                resp.errors.append(f"mutating: {e}")
                return
        if self.gate.enabled("PodValidatingWebhook"):
            ok, errs = validate_pod(pod)
            if not ok:
                resp.allowed = False
                resp.errors.extend(errs)

    def _admit_node(self, resp: AdmissionResponse, node: api.Node,
                    operation: str, old) -> None:
        try:
            resp.mutated = self.node_mutator.admit(node, old_node=old)
        except AdmissionError as e:
            resp.allowed = False
            resp.errors.append(str(e))
            return
        ok, errs = validate_node(node, old)
        if not ok:
            resp.allowed = False
            resp.errors.extend(errs)

    def _admit_configmap(self, resp: AdmissionResponse, data,
                         operation: str, _old) -> None:
        ok, errs = validate_slo_configmap(data)
        if not ok:
            resp.allowed = False
            resp.errors.extend(errs)

    def _admit_quota(self, resp: AdmissionResponse,
                     quota: api.ElasticQuota, operation: str,
                     _old) -> None:
        if self.quota_topology is None:
            return
        # both add and update run fill_defaults inside the guard; report
        # mutated only when defaulting actually changed the object (the
        # caller patches the object iff mutated)
        before = (None if operation == "Delete"
                  else dataclasses.asdict(quota))
        try:
            if operation == "Create":
                self.quota_topology.valid_add(quota)
            elif operation == "Update":
                self.quota_topology.valid_update(quota)
            elif operation == "Delete":
                self.quota_topology.valid_delete(quota.meta.name)
        except ValueError as e:
            resp.allowed = False
            resp.errors.append(str(e))
            return
        if before is not None:
            resp.mutated = dataclasses.asdict(quota) != before
