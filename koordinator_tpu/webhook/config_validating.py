"""slo-controller-config ConfigMap validation.

Capability parity with `pkg/webhook/cm/` — the validating handler runs a
checker per config key (plugins/sloconfig/{colocation,resource_threshold,
cpu_burst,resource_qos,system_config}_checker.go): each key must parse,
satisfy its field bounds, and keep node-override selectors non-empty.
The reference encodes bounds as struct validator tags on
apis/configuration; here they are explicit range checks on the typed
strategies (same constraints the koordlet enforcement path assumes).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Tuple

from koordinator_tpu.utils.naming import camel_to_snake as _snake

from koordinator_tpu.api import types as api
from koordinator_tpu.slo_controller.config import (
    CalculatePolicy,
    ColocationConfig,
    ColocationStrategy,
    ColocationStrategyOverride,
    validate_colocation_config,
)
from koordinator_tpu.slo_controller.nodeslo import StrategyOverride

# ConfigMap keys (sloconfig/config.go ConfigNameColocation etc.)
KEY_COLOCATION = "colocation-config"
KEY_RESOURCE_THRESHOLD = "resource-threshold-config"
KEY_CPU_BURST = "cpu-burst-config"
KEY_RESOURCE_QOS = "resource-qos-config"
KEY_SYSTEM = "system-config"

KNOWN_KEYS = (KEY_COLOCATION, KEY_RESOURCE_THRESHOLD, KEY_CPU_BURST,
              KEY_RESOURCE_QOS, KEY_SYSTEM)

_QOS_TIERS = ("LSE", "LSR", "LS", "BE", "SYSTEM", "NONE")
_QOS_KNOBS = {"groupIdentity": (-1, 2), "memoryPriority": (0, 12),
              "llcPercent": (0, 100), "mbaPercent": (0, 100),
              "memoryLow": (0, float("inf")), "memoryHigh": (0, float("inf")),
              "memoryWmarkRatio": (0, 100), "cpuIdle": (0, 1)}




def _build(cls, data: dict, where: str, errs: List[str]):
    """Construct a dataclass from camelCase JSON fields; unknown fields
    are rejected (the reference decodes with DisallowUnknownFields)."""
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for key, value in data.items():
        snake = _snake(key)
        if snake not in fields:
            errs.append(f"{where}: unknown field {key!r}")
            continue
        kwargs[snake] = value
    try:
        return cls(**kwargs)
    except (TypeError, ValueError) as e:
        errs.append(f"{where}: {e}")
        return cls()


def _overrides(data: dict, where: str,
               errs: List[str]) -> List[StrategyOverride]:
    out = []
    for i, entry in enumerate(data.get("nodeStrategies", [])):
        sel = entry.get("nodeSelector", {})
        if not sel:
            errs.append(f"{where}.nodeStrategies[{i}]: empty node selector")
        snake_fields = {_snake(k): v for k, v in entry.items()
                        if k != "nodeSelector"}
        out.append(StrategyOverride(node_selector=sel, fields=snake_fields))
    return out


# --- per-key checkers --------------------------------------------------------

def _check_colocation(raw: str, errs: List[str]) -> None:
    data = json.loads(raw)
    cluster = _build(ColocationStrategy,
                     {k: v for k, v in data.items()
                      if k not in ("nodeConfigs",)},
                     KEY_COLOCATION, errs)
    if isinstance(cluster.cpu_calculate_policy, str):
        try:
            cluster.cpu_calculate_policy = CalculatePolicy(
                cluster.cpu_calculate_policy)
        except ValueError:
            errs.append(f"{KEY_COLOCATION}: unknown cpuCalculatePolicy "
                        f"{cluster.cpu_calculate_policy!r}")
            cluster.cpu_calculate_policy = CalculatePolicy.USAGE
    if isinstance(cluster.memory_calculate_policy, str):
        try:
            cluster.memory_calculate_policy = CalculatePolicy(
                cluster.memory_calculate_policy)
        except ValueError:
            errs.append(f"{KEY_COLOCATION}: unknown memoryCalculatePolicy "
                        f"{cluster.memory_calculate_policy!r}")
            cluster.memory_calculate_policy = CalculatePolicy.USAGE
    overrides = []
    for i, entry in enumerate(data.get("nodeConfigs", [])):
        sel = entry.get("nodeSelector", {})
        if not sel:
            errs.append(f"{KEY_COLOCATION}.nodeConfigs[{i}]: empty selector")
        fields = {_snake(k): v for k, v in entry.items()
                  if k != "nodeSelector"}
        overrides.append(ColocationStrategyOverride(node_selector=sel,
                                                    fields=fields))
    errs.extend(validate_colocation_config(
        ColocationConfig(cluster_strategy=cluster,
                         node_overrides=overrides)))


def _check_threshold(raw: str, errs: List[str]) -> None:
    data = json.loads(raw)
    s = _build(api.ResourceThresholdStrategy,
               {k: v for k, v in data.items() if k != "nodeStrategies"},
               KEY_RESOURCE_THRESHOLD, errs)
    _overrides(data, KEY_RESOURCE_THRESHOLD, errs)
    for name, v in (("cpuSuppressThresholdPercent",
                     s.cpu_suppress_threshold_percent),
                    ("memoryEvictThresholdPercent",
                     s.memory_evict_threshold_percent),
                    ("cpuEvictBEUsageThresholdPercent",
                     s.cpu_evict_be_usage_threshold_percent)):
        if not 0 <= v <= 100:
            errs.append(f"{KEY_RESOURCE_THRESHOLD}: {name} out of [0,100]")
    if s.cpu_suppress_policy not in ("cpuset", "cfsQuota"):
        errs.append(f"{KEY_RESOURCE_THRESHOLD}: unknown cpuSuppressPolicy "
                    f"{s.cpu_suppress_policy!r}")
    lo = s.cpu_evict_satisfaction_lower_percent
    hi = s.cpu_evict_satisfaction_upper_percent
    if lo and not 0 < lo <= hi <= 100:
        errs.append(f"{KEY_RESOURCE_THRESHOLD}: satisfaction percents must "
                    f"satisfy 0 < lower <= upper <= 100")
    if s.memory_evict_lower_percent and \
            s.memory_evict_lower_percent >= s.memory_evict_threshold_percent:
        errs.append(f"{KEY_RESOURCE_THRESHOLD}: memoryEvictLowerPercent must "
                    f"be below memoryEvictThresholdPercent")


def _check_cpu_burst(raw: str, errs: List[str]) -> None:
    data = json.loads(raw)
    s = _build(api.CPUBurstStrategy,
               {k: v for k, v in data.items() if k != "nodeStrategies"},
               KEY_CPU_BURST, errs)
    _overrides(data, KEY_CPU_BURST, errs)
    if s.policy not in ("none", "cpuBurstOnly", "cfsQuotaBurstOnly", "auto"):
        errs.append(f"{KEY_CPU_BURST}: unknown policy {s.policy!r}")
    if not 0 < s.cpu_burst_percent <= 10000:
        errs.append(f"{KEY_CPU_BURST}: cpuBurstPercent out of (0,10000]")
    if s.cfs_quota_burst_percent < 100:
        errs.append(f"{KEY_CPU_BURST}: cfsQuotaBurstPercent must be >= 100")
    if not 0 < s.share_pool_threshold_percent <= 100:
        errs.append(f"{KEY_CPU_BURST}: sharePoolThresholdPercent out of "
                    f"(0,100]")


def _check_resource_qos(raw: str, errs: List[str]) -> None:
    data = json.loads(raw)
    _overrides(data, KEY_RESOURCE_QOS, errs)
    for tier, knobs in data.items():
        if tier == "nodeStrategies":
            continue
        if tier.upper() not in _QOS_TIERS:
            errs.append(f"{KEY_RESOURCE_QOS}: unknown QoS tier {tier!r}")
            continue
        if not isinstance(knobs, dict):
            errs.append(f"{KEY_RESOURCE_QOS}.{tier}: must be an object")
            continue
        for knob, value in knobs.items():
            bounds = _QOS_KNOBS.get(knob)
            if bounds is None:
                errs.append(f"{KEY_RESOURCE_QOS}.{tier}: unknown knob "
                            f"{knob!r}")
                continue
            lo, hi = bounds
            try:
                v = float(value)
            except (TypeError, ValueError):
                errs.append(f"{KEY_RESOURCE_QOS}.{tier}.{knob}: non-numeric")
                continue
            if not lo <= v <= hi:
                errs.append(f"{KEY_RESOURCE_QOS}.{tier}.{knob}: {v} out of "
                            f"[{lo},{hi}]")


def _check_system(raw: str, errs: List[str]) -> None:
    data = json.loads(raw)
    s = _build(api.SystemStrategy,
               {k: v for k, v in data.items() if k != "nodeStrategies"},
               KEY_SYSTEM, errs)
    _overrides(data, KEY_SYSTEM, errs)
    if s.min_free_kbytes_factor < 0:
        errs.append(f"{KEY_SYSTEM}: minFreeKbytesFactor must be >= 0")
    if not 10 <= s.watermark_scale_factor <= 1000:
        errs.append(f"{KEY_SYSTEM}: watermarkScaleFactor out of [10,1000] "
                    f"(kernel bounds)")


_CHECKERS: Dict[str, Callable[[str, List[str]], None]] = {
    KEY_COLOCATION: _check_colocation,
    KEY_RESOURCE_THRESHOLD: _check_threshold,
    KEY_CPU_BURST: _check_cpu_burst,
    KEY_RESOURCE_QOS: _check_resource_qos,
    KEY_SYSTEM: _check_system,
}


def validate_slo_configmap(data: Dict[str, str]
                           ) -> Tuple[bool, List[str]]:
    """Validate the whole slo-controller-config ConfigMap (the cm
    validating handler). Unknown keys are rejected so typos can't
    silently disable a strategy family."""
    errs: List[str] = []
    for key, raw in data.items():
        checker = _CHECKERS.get(key)
        if checker is None:
            errs.append(f"unknown config key {key!r} (known: "
                        f"{', '.join(KNOWN_KEYS)})")
            continue
        try:
            checker(raw, errs)
        except (ValueError, TypeError) as e:
            errs.append(f"{key}: unparseable: {e}")
    return not errs, errs
