"""ElasticQuota topology guard: admission validation of the quota tree.

Behavior parity with pkg/webhook/elasticquota/{quota_topology.go,
quota_topology_check.go} (SURVEY.md 2.3):
- self checks (validateQuotaSelfItem): min/max/sharedWeight nonnegative per
  dimension, min <= max on every declared dimension
- defaults (fillQuotaDefaultInformation :198-239): parent defaults to the
  root quota; tree id inherits from the parent; sharedWeight defaults to max
- topology (validateQuotaTopology + checks): parent must exist and have
  isParent=true; the tree id must match the parent's (and, on update, the
  children's); a child's max keys must equal its parent's max keys; the sum
  of sibling mins (including the candidate) must not exceed the parent min
  (checkMinQuotaValidate :212-245, skipped for direct root children and
  allowForceUpdate); parent changes with attached pods are forbidden
- namespace bindings are exclusive: one namespace annotates at most one
  quota (:71-76)
- delete guards (ValidDeleteQuota :153-196): system/root/default quotas are
  protected; quotas with children or bound pods cannot be deleted
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind

ROOT_QUOTA_NAME = "koordinator-root-quota"
SYSTEM_QUOTA_NAME = "koordinator-system-quota"
DEFAULT_QUOTA_NAME = "koordinator-default-quota"
_PROTECTED = (ROOT_QUOTA_NAME, SYSTEM_QUOTA_NAME, DEFAULT_QUOTA_NAME)


class QuotaTopologyError(ValueError):
    pass


class QuotaTopology:
    """In-memory mirror of the quota tree driving admission decisions.

    `pod_counter(quota_name) -> int` stands in for the pod list the
    reference queries on delete/parent-change (quota_topology.go:153-196).
    """

    def __init__(self,
                 pod_counter: Optional[Callable[[str], int]] = None):
        self.quotas: Dict[str, api.ElasticQuota] = {}
        self.children: Dict[str, Set[str]] = {ROOT_QUOTA_NAME: set()}
        self.namespace_to_quota: Dict[str, str] = {}
        self.pod_counter = pod_counter or (lambda _name: 0)

    # -- admission entry points ----------------------------------------------

    def valid_add(self, quota: api.ElasticQuota) -> None:
        name = quota.meta.name
        if name in self.quotas:
            raise QuotaTopologyError(f"quota already exists: {name}")
        for ns in quota.namespaces:
            bound = self.namespace_to_quota.get(ns)
            if bound is not None:
                raise QuotaTopologyError(
                    f"namespace {ns} is already bound to quota {bound}")
        self.fill_defaults(quota)
        self._validate_self(quota)
        self._validate_topology(quota, old=None)
        self.quotas[name] = quota
        self.children.setdefault(name, set())
        self.children.setdefault(quota.parent, set()).add(name)
        for ns in quota.namespaces:
            self.namespace_to_quota[ns] = name

    def valid_update(self, quota: api.ElasticQuota) -> None:
        name = quota.meta.name
        old = self.quotas.get(name)
        if old is None:
            raise QuotaTopologyError(f"quota does not exist: {name}")
        for ns in quota.namespaces:
            bound = self.namespace_to_quota.get(ns)
            if bound is not None and bound != name:
                raise QuotaTopologyError(
                    f"namespace {ns} is already bound to quota {bound}")
        self.fill_defaults(quota)
        self._validate_self(quota)
        self._validate_topology(quota, old=old)
        self.quotas[name] = quota
        if old.parent != quota.parent:
            self.children[old.parent].discard(name)
            self.children.setdefault(quota.parent, set()).add(name)
        for ns in old.namespaces:
            self.namespace_to_quota.pop(ns, None)
        for ns in quota.namespaces:
            self.namespace_to_quota[ns] = name

    def valid_delete(self, name: str) -> None:
        if name in _PROTECTED:
            raise QuotaTopologyError(f"can not delete quota {name}")
        quota = self.quotas.get(name)
        if quota is None:
            raise QuotaTopologyError(f"quota not found: {name}")
        if self.children.get(name):
            raise QuotaTopologyError(f"quota {name} has child quotas")
        if self.pod_counter(name) > 0:
            raise QuotaTopologyError(f"quota {name} has bound pods")
        self.children[quota.parent].discard(name)
        self.children.pop(name, None)
        del self.quotas[name]
        for ns in quota.namespaces:
            self.namespace_to_quota.pop(ns, None)

    # -- defaults ------------------------------------------------------------

    def fill_defaults(self, quota: api.ElasticQuota) -> None:
        if not quota.parent and quota.meta.name != ROOT_QUOTA_NAME:
            quota.parent = ROOT_QUOTA_NAME
        if not quota.tree_id and quota.parent != ROOT_QUOTA_NAME:
            parent = self.quotas.get(quota.parent)
            if parent is None:
                raise QuotaTopologyError(
                    f"fill quota {quota.meta.name} failed, parent not exist")
            quota.tree_id = parent.tree_id
        if not quota.shared_weight:
            quota.shared_weight = dict(quota.max)

    # -- checks --------------------------------------------------------------

    def _validate_self(self, quota: api.ElasticQuota) -> None:
        name = quota.meta.name
        for label, rl in (("max", quota.max), ("min", quota.min),
                          ("sharedWeight", quota.shared_weight)):
            bad = [k.name for k, v in rl.items() if v < 0]
            if bad:
                raise QuotaTopologyError(
                    f"{name} quota {label} < 0 in dimensions: {bad}")
        for kind, lo in quota.min.items():
            if lo > quota.max.get(kind, float("inf")):
                raise QuotaTopologyError(f"{name} min > max for {kind.name}")

    def _validate_topology(self, quota: api.ElasticQuota,
                           old: Optional[api.ElasticQuota]) -> None:
        name = quota.meta.name
        parent_name = quota.parent
        if parent_name != ROOT_QUOTA_NAME:
            parent = self.quotas.get(parent_name)
            if parent is None:
                raise QuotaTopologyError(
                    f"{name} has parent {parent_name} but it does not exist")
            if not parent.is_parent:
                raise QuotaTopologyError(
                    f"{name} has parent {parent_name} whose isParent is "
                    f"false")
            if quota.tree_id != parent.tree_id:
                raise QuotaTopologyError(
                    f"{name} tree id differs from parent {parent_name}: "
                    f"[{quota.tree_id}] vs [{parent.tree_id}]")
            # max dimensions must agree with the parent's
            if set(quota.max) != set(parent.max):
                raise QuotaTopologyError(
                    f"{name} max keys differ from parent {parent_name}")
            self._check_min_sum(quota, parent)
        if old is not None:
            for child_name in self.children.get(name, ()):
                child = self.quotas[child_name]
                if child.tree_id != quota.tree_id:
                    raise QuotaTopologyError(
                        f"{name} tree id differs from child {child_name}")
            if old.is_parent and not quota.is_parent \
                    and self.children.get(name):
                raise QuotaTopologyError(
                    f"{name} has children; isParent cannot become false")
            if not old.is_parent and quota.is_parent \
                    and self.pod_counter(name) > 0:
                raise QuotaTopologyError(
                    f"{name} has bound pods; isParent cannot become true")
            if old.parent != quota.parent and self.pod_counter(name) > 0:
                raise QuotaTopologyError(
                    f"{name} has bound pods; parent cannot change")

    def _check_min_sum(self, quota: api.ElasticQuota,
                       parent: api.ElasticQuota) -> None:
        """Σ sibling min (incl. candidate) <= parent min per dimension
        (checkMinQuotaValidate; skipped under allowForceUpdate)."""
        if quota.allow_force_update:
            return
        total: Dict[ResourceKind, float] = dict(quota.min)
        for sibling_name in self.children.get(parent.meta.name, ()):
            if sibling_name == quota.meta.name:
                continue
            for kind, v in self.quotas[sibling_name].min.items():
                total[kind] = total.get(kind, 0.0) + v
        for kind, v in total.items():
            if v > parent.min.get(kind, 0.0) + 1e-9:
                raise QuotaTopologyError(
                    f"all siblings' min > parent {parent.meta.name} min "
                    f"for {kind.name}")
