"""Pod validating admission.

Behavior parity with pkg/webhook/pod/validating/
cluster_colocation_profile.go (SURVEY.md 2.3):
- batch-tier resources require the BE QoS label (validateRequiredQoSClass
  :71-85)
- forbidden combinations (:104-122): QoS BE with priorityClass None/Prod;
  QoS LSR with None/Mid/Batch/Free
- LSR/LSE pods must request a nonzero, INTEGER number of CPUs
  (validateResources :123-140)
- on UPDATE, the QoS label, priority class, and koordinator priority label
  are immutable (:86-103)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    LABEL_POD_PRIORITY,
    LABEL_POD_QOS,
    PriorityClass,
    QoSClass,
    ResourceKind,
    priority_class_of,
)

_FORBIDDEN = {
    QoSClass.BE: (PriorityClass.NONE, PriorityClass.PROD),
    QoSClass.LSR: (PriorityClass.NONE, PriorityClass.MID,
                   PriorityClass.BATCH, PriorityClass.FREE),
}


def validate_pod(new_pod: api.Pod,
                 old_pod: Optional[api.Pod] = None) -> Tuple[bool, List[str]]:
    """Returns (allowed, reasons)."""
    errs: List[str] = []
    if old_pod is not None:
        if old_pod.qos is not new_pod.qos:
            errs.append(f"labels.{LABEL_POD_QOS}: field is immutable")
        if (priority_class_of(old_pod.priority, old_pod.priority_class_label,
                              old_pod.priority_class_name)
                is not priority_class_of(new_pod.priority,
                                         new_pod.priority_class_label,
                                         new_pod.priority_class_name)):
            errs.append("spec.priority: field is immutable")
        if (old_pod.meta.labels.get(LABEL_POD_PRIORITY)
                != new_pod.meta.labels.get(LABEL_POD_PRIORITY)):
            errs.append(f"labels.{LABEL_POD_PRIORITY}: field is immutable")

    batch_cpu = new_pod.requests.get(ResourceKind.BATCH_CPU, 0.0)
    batch_mem = new_pod.requests.get(ResourceKind.BATCH_MEMORY, 0.0)
    if (batch_cpu or batch_mem) and new_pod.qos is not QoSClass.BE:
        errs.append(
            f"labels.{LABEL_POD_QOS}: must specify koordinator QoS BE with "
            f"koordinator colocation resources")

    pc = priority_class_of(new_pod.priority, new_pod.priority_class_label,
                           new_pod.priority_class_name)
    forbidden = _FORBIDDEN.get(new_pod.qos, ())
    if pc in forbidden:
        errs.append(
            f"{LABEL_POD_QOS}={new_pod.qos.name} and priorityClass="
            f"{pc.name.lower()} cannot be used in combination")

    if new_pod.qos in (QoSClass.LSR, QoSClass.LSE):
        cpu = new_pod.requests.get(ResourceKind.CPU, 0.0)
        if cpu == 0:
            errs.append("LSR Pod must declare the requested CPUs")
        elif cpu % 1000 != 0:
            errs.append("the requested CPUs of LSR Pod must be integer")

    return not errs, errs
