"""Node mutating/validating webhooks.

Capability parity with `pkg/webhook/node/` — the mutating handler's
NodeResourceAmplificationPlugin (plugins/resourceamplification/
resource_amplification.go:60-165) and the validating handler's ratio
checks. Amplification lets the scheduler overcommit a node by a
per-resource ratio: the webhook snapshots the kubelet's raw allocatable
into an annotation and publishes `raw * ratio` as the visible
allocatable; clearing the ratio annotation restores raw accounting.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_AMPLIFICATION_RATIOS,
    ANNOTATION_NODE_RAW_ALLOCATABLE,
    ResourceKind,
)

# only these dimensions amplify (supportedResources in the reference:
# cpu + memory; extended/batch resources are derived, never amplified)
SUPPORTED = (ResourceKind.CPU, ResourceKind.MEMORY)


class AdmissionError(ValueError):
    """Raised to REJECT the admission request (the reference's non-nil
    Admit/Validate error -> admission.Errored response)."""


def _parse_ratios(annotations: Dict[str, str]) -> Dict[ResourceKind, float]:
    raw = annotations.get(ANNOTATION_NODE_AMPLIFICATION_RATIOS, "")
    if not raw:
        return {}
    try:
        data = json.loads(raw)
        return {ResourceKind[str(name).upper()]: float(ratio)
                for name, ratio in data.items()}
    except (ValueError, KeyError, AttributeError, TypeError) as e:
        raise AdmissionError(
            f"bad {ANNOTATION_NODE_AMPLIFICATION_RATIOS} annotation: "
            f"{e}") from None


def _parse_raw_allocatable(annotations: Dict[str, str]
                           ) -> Dict[ResourceKind, float]:
    raw = annotations.get(ANNOTATION_NODE_RAW_ALLOCATABLE, "")
    if not raw:
        return {}
    try:
        return {ResourceKind[str(k).upper()]: float(v)
                for k, v in json.loads(raw).items()}
    except (ValueError, KeyError, AttributeError, TypeError) as e:
        raise AdmissionError(
            f"bad {ANNOTATION_NODE_RAW_ALLOCATABLE} annotation: {e}") \
            from None


def _store_raw_allocatable(node: api.Node,
                           values: Dict[ResourceKind, float]) -> None:
    node.meta.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
        {k.name.lower(): v for k, v in values.items()})


class NodeMutator:
    """Admit for CREATE/UPDATE (resource_amplification.go handleCreate/
    handleUpdate): no ratio annotation -> restore raw allocatable and
    drop the stash; else stash raw (first time, or when the kubelet
    changed a supported dimension vs old_node) and publish amplified
    values for every ratio > 1. Raises AdmissionError (= reject) on a
    malformed annotation — mutating runs BEFORE validating, so parse
    failures cannot rely on validate_node to shield them."""

    def admit(self, node: api.Node,
              old_node: Optional[api.Node] = None) -> bool:
        anns = node.meta.annotations
        if not anns.get(ANNOTATION_NODE_AMPLIFICATION_RATIOS):
            # feature turned off: un-amplify back to the stashed raw
            # values, then drop the stash (the docstring's "clearing the
            # ratio annotation restores raw accounting")
            stashed = _parse_raw_allocatable(anns)
            for kind, value in stashed.items():
                node.allocatable[kind] = value
            return anns.pop(ANNOTATION_NODE_RAW_ALLOCATABLE, None) is not None
        if not node.allocatable:
            return False
        ratios = _parse_ratios(anns)
        raw = _parse_raw_allocatable(anns)
        changed = False
        if not raw or self._kubelet_changed(node, old_node):
            raw = {k: node.allocatable[k] for k in SUPPORTED
                   if k in node.allocatable}
            if raw:
                _store_raw_allocatable(node, raw)
                changed = True  # the stash itself is part of the patch
        for kind in SUPPORTED:
            ratio = ratios.get(kind, 0.0)
            if ratio <= 1.0 or kind not in raw:
                continue  # missing dims stay raw (":146-157")
            node.allocatable[kind] = raw[kind] * ratio
            changed = True
        return changed

    @staticmethod
    def _kubelet_changed(node: api.Node,
                         old_node: Optional[api.Node]) -> bool:
        # only the kubelet rewrites native allocatable; a change vs the
        # old object means the stash is stale (":104-112")
        if old_node is None:
            return False
        return any(node.allocatable.get(k) != old_node.allocatable.get(k)
                   for k in SUPPORTED)


def validate_node(node: api.Node,
                  old_node: Optional[api.Node] = None
                  ) -> Tuple[bool, List[str]]:
    """Validating handler: the amplification/raw annotations must parse
    and every ratio must be >= 1 (node/validating + plugin Validate)."""
    errs: List[str] = []
    try:
        ratios = _parse_ratios(node.meta.annotations)
        for kind, ratio in ratios.items():
            if ratio < 1.0:
                errs.append(f"amplification ratio for {kind.name.lower()} "
                            f"is {ratio}, must be >= 1")
    except AdmissionError as e:
        errs.append(str(e))
    try:
        _parse_raw_allocatable(node.meta.annotations)
    except AdmissionError as e:
        errs.append(str(e))
    return not errs, errs
