"""Shared utilities (reference pkg/util, SURVEY.md 2.7)."""
