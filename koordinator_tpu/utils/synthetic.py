"""Vectorized synthetic cluster/workload generation for benchmarks and
scale tests.

The typed-object path (SnapshotBuilder) is the production ingest; at 100k
pods a per-object Python loop would dominate the benchmark, so this module
builds the columnar pytrees directly with numpy. Semantics match the
builder (same estimator math, same columns) — cross-checked by tests.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from koordinator_tpu.api.extension import NUM_RESOURCES, PriorityClass, QoSClass, ResourceKind
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    DeviceState,
    GangState,
    MAX_QUOTA_DEPTH,
    NodeState,
    NUM_AGG,
    NUM_AUX_TYPES,
    NUM_DEV_DIMS,
    PER_POD_FIELDS as _PER_POD_FIELDS,
    PodBatch,
    QuotaState,
    ReservationState,
    zeros_devices,
)

R = NUM_RESOURCES
CPU, MEM = int(ResourceKind.CPU), int(ResourceKind.MEMORY)

# live reservation slot hold (synthetic_cluster num_reservations > 0);
# module-level so full_gate_pods can sample owners that actually FIT
RESV_SLOT_CPU, RESV_SLOT_MEM = 4000.0, 8192.0
BCPU, BMEM = int(ResourceKind.BATCH_CPU), int(ResourceKind.BATCH_MEMORY)


def estimate_vectorized(requests: np.ndarray, limits: np.ndarray,
                        priority_class: np.ndarray,
                        cpu_factor: float = 85.0,
                        mem_factor: float = 70.0) -> np.ndarray:
    """Vectorized DefaultEstimator (estimator/default_estimator.go:62-110)
    over [P, R] request/limit columns for the cpu/memory weight dims."""
    p = requests.shape[0]
    out = np.zeros((p, R), np.float32)
    is_batch = priority_class == int(PriorityClass.BATCH)
    is_mid = priority_class == int(PriorityClass.MID)
    for kind, factor, default in ((CPU, cpu_factor, 250.0),
                                  (MEM, mem_factor, 200.0)):
        tier_dim = np.where(
            is_batch, kind + 2, np.where(is_mid, kind + 4, kind))
        req = np.take_along_axis(requests, tier_dim[:, None], 1)[:, 0]
        lim = np.take_along_axis(limits, tier_dim[:, None], 1)[:, 0]
        use_lim = lim > req
        qty = np.where(use_lim, lim, req)
        f = np.where(use_lim, 100.0, factor)
        est = np.floor(qty.astype(np.float64) * f / 100.0 + 0.5)
        est = np.where(lim > 0, np.minimum(est, lim), est)
        est = np.where(qty == 0, default, est)
        out[:, kind] = est.astype(np.float32)
    return out


def synthetic_cluster(num_nodes: int, seed: int = 0,
                      max_quotas: int = 64, max_gangs: int = 64,
                      num_quotas: int = 0, num_gangs: int = 0,
                      gang_min_member: int = 8,
                      batch_overcommit_ratio: float = 0.5,
                      usage_cpu_frac: Tuple[float, float] = (0.0, 0.6),
                      gpu_node_frac: float = 0.0,
                      gpus_per_node: int = 8,
                      gpu_memory_mib: float = 81920.0,
                      num_reservations: int = 0,
                      now_version: int = 0) -> ClusterSnapshot:
    """A realistic colocation cluster: heterogeneous nodes, fresh
    NodeMetrics, batch-tier overcommit resources, a two-level quota tree,
    and gangs. All arrays are host numpy; upload via SnapshotStore."""
    rng = np.random.default_rng(seed)
    n = num_nodes
    f32 = np.float32

    cpu_alloc = rng.choice([32000, 64000, 96000], n).astype(f32)
    mem_alloc = (rng.choice([128, 256, 384], n) * 1024).astype(f32)
    alloc = np.zeros((n, R), f32)
    alloc[:, CPU] = cpu_alloc
    alloc[:, MEM] = mem_alloc
    # slo-controller batch overcommit: Batch = Total - Reserved - Used
    usage = np.zeros((n, R), f32)
    usage[:, CPU] = (rng.uniform(*usage_cpu_frac, n) * cpu_alloc).astype(f32)
    usage[:, MEM] = (rng.uniform(0.1, 0.7, n) * mem_alloc).astype(f32)
    alloc[:, BCPU] = np.maximum(
        (cpu_alloc - usage[:, CPU]) * batch_overcommit_ratio, 0)
    alloc[:, BMEM] = np.maximum(
        (mem_alloc - usage[:, MEM]) * batch_overcommit_ratio, 0)

    agg = np.zeros((n, NUM_AGG, R), f32)
    agg[:] = usage[:, None, :]
    agg[:, 2:] *= 1.15  # p90+ slightly above avg

    nodes = NodeState(
        allocatable=alloc,
        requested=np.zeros((n, R), f32),
        usage=usage,
        prod_usage=usage * 0.8,
        agg_usage=agg,
        assigned_estimated=np.zeros((n, R), f32),
        assigned_correction=np.zeros((n, R), f32),
        prod_assigned_estimated=np.zeros((n, R), f32),
        prod_assigned_correction=np.zeros((n, R), f32),
        metric_fresh=np.ones((n,), bool),
        has_agg=np.ones((n,), bool),
        schedulable=np.ones((n,), bool),
        label_group=np.zeros((n,), np.int32),
        numa_cap=np.zeros((n, 4, 2), f32),
        numa_free=np.zeros((n, 4, 2), f32),
        numa_valid=np.zeros((n, 4), bool),
        numa_policy=np.zeros((n,), np.int32),
        cpu_amplification=np.ones((n,), f32),
        taint_group=np.zeros((n,), np.int32),
    )

    q = max_quotas
    quota_min = np.zeros((q, R), f32)
    quota_max = np.full((q, R), np.inf, f32)
    weight = np.zeros((q, R), f32)
    parent = np.full((q,), -1, np.int32)
    ancestors = np.zeros((q, q), bool)
    depth_anc = np.full((q, MAX_QUOTA_DEPTH), -1, np.int32)
    qvalid = np.zeros((q,), bool)
    if num_quotas > 0:
        # quota 0 = root; 1..num_quotas-1 children sharing the cluster
        total_cpu = float(cpu_alloc.sum())
        total_mem = float(mem_alloc.sum())
        qvalid[:num_quotas] = True
        quota_max[0, CPU], quota_max[0, MEM] = total_cpu, total_mem
        ancestors[0, 0] = True
        depth_anc[0, 0] = 0
        for i in range(1, num_quotas):
            share = rng.uniform(0.05, 0.3)
            quota_max[i, CPU] = total_cpu * share
            quota_max[i, MEM] = total_mem * share
            quota_min[i, CPU] = total_cpu * share * 0.2
            quota_min[i, MEM] = total_mem * share * 0.2
            parent[i] = 0
            ancestors[i, i] = True
            ancestors[i, 0] = True
            depth_anc[i, 0] = 0
            depth_anc[i, 1] = i
        weight = np.where(np.isinf(quota_max), 1.0, quota_max).astype(f32)
    quotas = QuotaState(
        min=quota_min, max=quota_max, shared_weight=weight, parent=parent,
        ancestors=ancestors, depth_ancestor=depth_anc,
        used=np.zeros((q, R), f32), demand=np.zeros((q, R), f32),
        allow_lent=np.ones((q,), bool),
        runtime=quota_max.copy(), valid=qvalid)

    g = max_gangs
    gangs = GangState(
        min_member=np.full((g,), gang_min_member, np.int32),
        member_count=np.full((g,), gang_min_member, np.int32),
        assumed=np.zeros((g,), np.int32),
        strict=np.ones((g,), bool),
        satisfied=np.zeros((g,), bool),
        valid=np.arange(g) < num_gangs,
    )
    n_inst = gpus_per_node if gpu_node_frac > 0 else 0
    # Reservation slots: 0 by default — the slim workloads never
    # consume reservations, and a ZERO-length slot axis compiles the
    # virtual-node columns and the AllocateOnce [P, P] ordering
    # machinery OUT of their programs (the previous fixed 8 invalid
    # slots cost a full-width inner-step op for nothing). The FULL-gate
    # cluster requests LIVE slots instead (num_reservations > 0):
    # valid, node-hosted, owner-restricted holds whose capacity is
    # charged on the hosting node (restore semantics — consumers draw
    # from the slot, not the node's open pool), so the flagship
    # exercises the reservation gate semantically, not as dead weight.
    v = int(num_reservations)
    if v > n:
        raise ValueError(f"num_reservations={v} needs at least that many "
                         f"nodes; got {n}")
    r_nodes = np.full((v,), -1, np.int32)
    r_free = np.zeros((v, R), f32)
    if v:
        rrng = np.random.default_rng(seed + 41)
        r_nodes = rrng.choice(n, v, replace=False).astype(np.int32)
        r_free[:, CPU] = RESV_SLOT_CPU
        r_free[:, MEM] = RESV_SLOT_MEM
        req = nodes.requested.copy()
        req[r_nodes, CPU] += RESV_SLOT_CPU
        req[r_nodes, MEM] += RESV_SLOT_MEM
        nodes = nodes.replace(requested=req)
    reservations = ReservationState(
        node=r_nodes,
        free=r_free,
        owner_group=np.arange(v, dtype=np.int32),
        allocate_once=(np.arange(v) % 2 == 0),
        valid=np.ones((v,), bool),
        gpu_free=np.zeros((v, n_inst, NUM_DEV_DIMS), f32),
        gpu_valid=np.zeros((v, n_inst), bool),
        numa_free=np.zeros((v, 4, 2), f32),
        numa_valid=np.zeros((v, 4), bool),
    )
    if gpu_node_frac > 0:
        i = gpus_per_node
        is_gpu_node = rng.uniform(size=n) < gpu_node_frac
        gpu_total = np.zeros((n, NUM_DEV_DIMS), f32)
        gpu_total[is_gpu_node] = (100.0, gpu_memory_mib, 100.0)
        # aggregate device capacity rides node allocatable too (the device
        # plugin reports extended resources), feeding the cheap node-level
        # fit gate before the exact per-instance gates
        alloc = nodes.allocatable
        alloc[is_gpu_node, int(ResourceKind.GPU_CORE)] = i * 100.0
        alloc[is_gpu_node, int(ResourceKind.GPU_MEMORY)] = i * gpu_memory_mib
        nodes = nodes.replace(allocatable=alloc)
        gpu_free = np.broadcast_to(gpu_total[:, None, :],
                                   (n, i, NUM_DEV_DIMS)).copy()
        gpu_valid = np.broadcast_to(is_gpu_node[:, None], (n, i)).copy()
        # GPUs split across 2 NUMA nodes, 2 per PCIe root (A100-like)
        inst = np.arange(i)
        gpu_numa = np.broadcast_to((inst * 2 // max(i, 1))[None, :],
                                   (n, i)).astype(np.int32).copy()
        gpu_pcie = np.broadcast_to((inst // 2)[None, :],
                                   (n, i)).astype(np.int32).copy()
        gpu_numa[~is_gpu_node] = -1
        gpu_pcie[~is_gpu_node] = -1
        devices = DeviceState(
            gpu_total=gpu_total, gpu_free=gpu_free, gpu_valid=gpu_valid,
            gpu_numa=gpu_numa, gpu_pcie=gpu_pcie,
            aux_free=np.zeros((n, NUM_AUX_TYPES, 0), f32),
            aux_valid=np.zeros((n, NUM_AUX_TYPES, 0), bool),
        )
    else:
        devices = zeros_devices(n)
    return ClusterSnapshot(nodes=nodes, quotas=quotas, gangs=gangs,
                           reservations=reservations, devices=devices,
                           version=np.int32(now_version))


def synthetic_pods(num_pods: int, seed: int = 1,
                   prod_frac: float = 0.6,
                   num_quotas: int = 0, num_gangs: int = 0,
                   gang_min_member: int = 8,
                   gpu_pod_frac: float = 0.0) -> PodBatch:
    """A pending-pod batch: prod pods request native cpu/mem, batch pods
    request batch-tier resources (webhook translation, SURVEY.md 2.3)."""
    rng = np.random.default_rng(seed)
    p = num_pods
    f32 = np.float32
    is_prod = rng.uniform(size=p) < prod_frac
    prio_class = np.where(is_prod, int(PriorityClass.PROD),
                          int(PriorityClass.BATCH)).astype(np.int8)
    priority = np.where(is_prod, 9000, 5000).astype(np.int32) + \
        rng.integers(0, 999, p).astype(np.int32)

    cpu_req = (rng.integers(1, 16, p) * 500).astype(f32)
    mem_req = (rng.integers(1, 32, p) * 512).astype(f32)
    requests = np.zeros((p, R), f32)
    requests[is_prod, CPU] = cpu_req[is_prod]
    requests[is_prod, MEM] = mem_req[is_prod]
    requests[~is_prod, BCPU] = cpu_req[~is_prod]
    requests[~is_prod, BMEM] = mem_req[~is_prod]
    limits = np.zeros((p, R), f32)

    gpu_ratio = np.zeros((p,), f32)
    if gpu_pod_frac > 0:
        # mix of shared (half-GPU), full single, and multi-GPU trainers
        is_gpu = rng.uniform(size=p) < gpu_pod_frac
        shape = rng.choice([50, 100, 200, 400], p,
                           p=[0.4, 0.3, 0.2, 0.1]).astype(f32)
        gpu_ratio = np.where(is_gpu, shape, 0.0).astype(f32)
        requests[:, int(ResourceKind.GPU_CORE)] = np.where(
            is_gpu, shape, 0.0)

    estimated = estimate_vectorized(requests, limits, prio_class)

    gang_id = np.full((p,), -1, np.int32)
    if num_gangs > 0:
        members = num_gangs * gang_min_member
        gang_id[:members] = np.repeat(np.arange(num_gangs, dtype=np.int32),
                                      gang_min_member)
    quota_id = np.full((p,), -1, np.int32)
    if num_quotas > 1:
        quota_id = rng.integers(1, num_quotas, p).astype(np.int32)

    return PodBatch(
        requests=requests, estimated=estimated,
        qos=np.where(is_prod, int(QoSClass.LS), int(QoSClass.BE)).astype(np.int8),
        priority_class=prio_class, priority=priority,
        gang_id=gang_id, quota_id=quota_id,
        selector_id=np.full((p,), -1, np.int32),
        selector_match=np.zeros((8, 64), bool),
        reservation_owner=np.full((p,), -1, np.int32),
        gpu_ratio=gpu_ratio,
        numa_single=np.zeros((p,), bool),
        daemonset=np.zeros((p,), bool),
        toleration_id=np.zeros((p,), np.int32),
        tol_forbid=np.zeros((1, 1), bool),
        tol_prefer=np.zeros((1, 1), f32),
        spread_id=np.full((p,), -1, np.int32),
        spread_carrier=np.zeros((p, 1), bool),
        spread_member=np.zeros((p, 1), bool),
        spread_max_skew=np.ones((1,), f32),
        spread_domain=np.full((1, 1), -1, np.int32),
        spread_count0=np.zeros((1, 1), f32),
        spread_dvalid=np.zeros((1, 1), bool),
        anti_id=np.full((p,), -1, np.int32),
        anti_member=np.zeros((p, 1), bool),
        anti_carrier=np.zeros((p, 1), bool),
        anti_domain=np.full((1, 1), -1, np.int32),
        anti_count0=np.zeros((1, 1), f32),
        anti_carrier_count0=np.zeros((1, 1), f32),
        aff_id=np.full((p,), -1, np.int32),
        aff_carrier=np.zeros((p, 1), bool),
        aff_member=np.zeros((p, 1), bool),
        aff_domain=np.full((1, 1), -1, np.int32),
        aff_count0=np.zeros((1, 1), f32),
        valid=np.ones((p,), bool),
    )


def with_two_numa_zones(snap: ClusterSnapshot) -> ClusterSnapshot:
    """Populate every node with two NUMA zones at half capacity each
    (the dual-socket shape; shared by the full-gate flagship workload
    and BASELINE config 2 so the zone model cannot drift). The zone
    AXIS is compacted to exactly 2: every [.., Z, 2] intermediate in
    the zone kernels ([P, N, Z, 2] score/fit tensors) halves versus the
    4-slot default, and the reservation zone columns are sliced to
    match (the extended-pool concat requires one Z)."""
    nodes = snap.nodes
    alloc = np.asarray(nodes.allocatable)
    n = alloc.shape[0]
    z = 2
    resv_valid = np.asarray(snap.reservations.numa_valid)
    if resv_valid.shape[1] < z:
        raise ValueError(
            "with_two_numa_zones needs >= 2 reservation zone slots to "
            "keep the node/reservation zone axes consistent")
    if resv_valid[:, z:].any():
        raise ValueError(
            "with_two_numa_zones would silently drop reservation NUMA "
            "holds in zones >= 2; this helper is for dual-socket "
            "workloads only")
    numa_cap = np.zeros((n, z, 2), np.float32)
    numa_cap[:, 0, 0] = alloc[:, CPU] / 2
    numa_cap[:, 1, 0] = alloc[:, CPU] / 2
    numa_cap[:, 0, 1] = alloc[:, MEM] / 2
    numa_cap[:, 1, 1] = alloc[:, MEM] / 2
    numa_valid = np.ones((n, z), bool)
    resv = snap.reservations
    return snap.replace(
        nodes=nodes.replace(
            numa_cap=numa_cap, numa_free=numa_cap.copy(),
            numa_valid=numa_valid),
        reservations=resv.replace(
            numa_free=np.asarray(resv.numa_free)[:, :z],
            numa_valid=np.asarray(resv.numa_valid)[:, :z]))


def full_gate_reservations(num_nodes: int) -> int:
    """Live-slot count shared by full_gate_cluster and full_gate_pods
    (owner ids must line up with slot owner_groups)."""
    return min(64, num_nodes // 2)


def full_gate_cluster(num_nodes: int, seed: int = 0,
                      num_quotas: int = 32, max_quotas: int = 64,
                      num_gangs: int = 64, max_gangs: int = 64,
                      gpu_node_frac: float = 0.25,
                      gpus_per_node: int = 8,
                      num_reservations: int = None) -> ClusterSnapshot:
    """The FULL-gate flagship cluster: everything the slim bench cluster
    has, plus two populated NUMA zones per node, GPU nodes with
    per-instance pools, and a 3-class taint landscape (none/dedicated/
    gpu-exclusive). The reference's hot loop runs every registered
    plugin for every pod (framework_extender.go:204-259); this workload
    makes the batched program compile every gate in."""
    if num_reservations is None:
        num_reservations = full_gate_reservations(num_nodes)
    snap = synthetic_cluster(num_nodes, seed=seed, num_quotas=num_quotas,
                             max_quotas=max_quotas, num_gangs=num_gangs,
                             max_gangs=max_gangs,
                             gpu_node_frac=gpu_node_frac,
                             gpus_per_node=gpus_per_node,
                             num_reservations=num_reservations)
    snap = with_two_numa_zones(snap)
    rng = np.random.default_rng(seed + 17)
    # taint classes: 0 = untainted, 1 = dedicated, 2 = gpu-exclusive
    taint_group = rng.choice(3, num_nodes,
                             p=[0.8, 0.15, 0.05]).astype(np.int32)
    return snap.replace(nodes=snap.nodes.replace(taint_group=taint_group))


def full_gate_pods(num_pods: int, num_nodes: int, seed: int = 1,
                   num_quotas: int = 32, num_gangs: int = 64,
                   gang_min_member: int = 8, num_zones: int = 16,
                   gpu_pod_frac: float = 0.1,
                   numa_bind_frac: float = 0.33,
                   n_spread_groups: int = 8, spread_frac: float = 0.15,
                   max_skew: float = 64.0,
                   n_anti_groups: int = 16, anti_members: int = 64,
                   n_aff_groups: int = 8, aff_members: int = 48,
                   num_reservations: int = None) -> PodBatch:
    """The FULL-gate flagship workload: quota + gang pods plus NUMA-bound
    prod pods, GPU pods, three toleration classes, PodTopologySpread
    groups over zone domains, required anti-affinity over hostname
    domains, and affinity groups co-locating over zones. Every static
    gate switch is on, so schedule_batch compiles the complete plugin
    chain — the faithful analogue of the reference running all plugins
    per pod."""
    pods = synthetic_pods(num_pods, seed=seed, num_quotas=num_quotas,
                          num_gangs=num_gangs,
                          gang_min_member=gang_min_member,
                          gpu_pod_frac=gpu_pod_frac)
    rng = np.random.default_rng(seed + 29)
    p = num_pods
    f32 = np.float32

    # a third of prod (native-CPU) pods are single-NUMA bound (the
    # resource-spec annotation + LSR path, bench config 2 semantics)
    is_prod = np.asarray(pods.priority_class) == int(PriorityClass.PROD)
    numa_single = is_prod & (rng.uniform(size=p) < numa_bind_frac)

    # tolerations: set 0 tolerates nothing, set 1 tolerates dedicated,
    # set 2 tolerates both taint classes
    toleration_id = rng.choice(3, p, p=[0.7, 0.2, 0.1]).astype(np.int32)
    tol_forbid = np.array([[False, True, True],
                           [False, False, True],
                           [False, False, False]])
    # dedicated nodes carry one PreferNoSchedule taint for the
    # non-tolerating set (engages the taint score penalty too)
    tol_prefer = np.array([[0.0, 1.0, 1.0],
                           [0.0, 0.0, 1.0],
                           [0.0, 0.0, 0.0]], f32)

    # MULTI-CONSTRAINT spread, the upstream default profile: every
    # spread pod carries a ZONE constraint (group g) AND a HOSTNAME
    # constraint (companion group g + n_spread_groups) together — the
    # carrier matrix gates it by both. Zone groups spread over
    # num_zones domains; hostname groups spread over per-node domains
    # with a loose skew (the kube-scheduler zone+hostname pair).
    zone_of_node = (np.arange(num_nodes) % num_zones).astype(np.int32)
    host_of_node = np.arange(num_nodes, dtype=np.int32)
    n_sg_total = 2 * n_spread_groups
    d_cap = max(num_zones, num_nodes)
    spread_domain = np.empty((n_sg_total, num_nodes), np.int32)
    spread_domain[:n_spread_groups] = zone_of_node
    spread_domain[n_spread_groups:] = host_of_node
    in_spread = rng.uniform(size=p) < spread_frac
    sgrp = rng.integers(0, n_spread_groups, p).astype(np.int32)
    spread_id = np.where(in_spread, sgrp, -1).astype(np.int32)
    spread_member = np.zeros((p, n_sg_total), bool)
    spread_carrier = np.zeros((p, n_sg_total), bool)
    rows = np.flatnonzero(in_spread)
    spread_member[rows, sgrp[in_spread]] = True
    spread_member[rows, sgrp[in_spread] + n_spread_groups] = True
    spread_carrier[rows, sgrp[in_spread]] = True
    spread_carrier[rows, sgrp[in_spread] + n_spread_groups] = True
    spread_count0 = np.zeros((n_sg_total, d_cap), f32)
    spread_dvalid = np.zeros((n_sg_total, d_cap), bool)
    spread_dvalid[:n_spread_groups, :num_zones] = True
    spread_dvalid[n_spread_groups:, :num_nodes] = True
    # hostname skew stays loose relative to members-per-group so the
    # workload remains schedulable while the per-node cap still gates
    host_skew = max(float(np.ceil(p * spread_frac / n_spread_groups
                                  / max(num_nodes, 1))) + 3.0, 4.0)
    spread_max_skew = np.concatenate([
        np.full((n_spread_groups,), max_skew, f32),
        np.full((n_spread_groups,), host_skew, f32)])

    # group memberships scale DOWN with small batches (the constrained
    # pods stay <= ~half the batch) instead of crashing an undersized
    # run with an opaque sampling error
    anti_members = max(min(anti_members, p // (4 * n_anti_groups)), 1)
    aff_members = max(min(aff_members, p // (4 * n_aff_groups)), 1)
    total_anti = n_anti_groups * anti_members
    total_aff = n_aff_groups * aff_members
    if total_anti + total_aff > p:
        raise ValueError(
            f"full_gate_pods needs at least {n_anti_groups + n_aff_groups}"
            f" pods for {n_anti_groups} anti + {n_aff_groups} affinity "
            f"groups; got {p}")

    # required anti-affinity over HOSTNAME domains: each group's
    # carriers must land on distinct nodes (the kv-service shape)
    host_domain = np.arange(num_nodes, dtype=np.int32)
    anti_domain = np.broadcast_to(
        host_domain, (n_anti_groups, num_nodes)).copy()
    anti_id = np.full((p,), -1, np.int32)
    anti_member = np.zeros((p, n_anti_groups), bool)
    anti_carrier = np.zeros((p, n_anti_groups), bool)
    a_idx = rng.choice(p, total_anti, replace=False)
    a_grp = np.repeat(np.arange(n_anti_groups, dtype=np.int32),
                      anti_members)
    anti_id[a_idx] = a_grp
    anti_member[a_idx, a_grp] = True
    anti_carrier[a_idx, a_grp] = True
    anti_count0 = np.zeros((n_anti_groups, num_nodes), f32)
    anti_carrier_count0 = np.zeros((n_anti_groups, num_nodes), f32)

    # affinity groups co-locating over zones (self-bootstrap opens the
    # first domain, the rest must follow); groups come in PAIRS — every
    # odd group's member ALSO carries the even partner's term
    # (multi-term pods: both groups must hold where they land). All
    # members are dual so the pair CONVERGES: a partial overlap would
    # let the two groups bootstrap different zones and strand the
    # multi-term pods with an empty intersection — a workload bug, not
    # a scheduler property.
    aff_domain = np.broadcast_to(
        zone_of_node, (n_aff_groups, num_nodes)).copy()
    aff_id = np.full((p,), -1, np.int32)
    aff_member = np.zeros((p, n_aff_groups), bool)
    aff_carrier = np.zeros((p, n_aff_groups), bool)
    # disjoint from the anti pods so one pod never carries both terms
    remaining = np.setdiff1d(np.arange(p), a_idx, assume_unique=False)
    f_idx = rng.choice(remaining, total_aff, replace=False)
    f_grp = np.repeat(np.arange(n_aff_groups, dtype=np.int32),
                      aff_members)
    aff_id[f_idx] = f_grp
    aff_member[f_idx, f_grp] = True
    aff_carrier[f_idx, f_grp] = True
    for g in range(1, n_aff_groups, 2):
        dual = f_idx[(f_grp == g)]
        aff_member[dual, g - 1] = True
        aff_carrier[dual, g - 1] = True
    aff_count0 = np.zeros((n_aff_groups, num_zones), f32)

    # reservation owners: two pods compete for each live slot of the
    # full-gate cluster (num_reservations defaults to the shared
    # full_gate_reservations formula so owner ids line up with slot
    # owner_groups) — the AllocateOnce single-winner ordering and the
    # slot virtual-node columns run against real consumers, not dead
    # weight. Owners are sampled from pods that can actually FIT the
    # slot hold: requests within (RESV_SLOT_CPU, RESV_SLOT_MEM) on the
    # prod dims and zero elsewhere (excludes batch-tier, device and
    # CPU-bind pods — the slots carry no zone/instance holds).
    from koordinator_tpu.scheduler.plugins import deviceshare
    v = full_gate_reservations(num_nodes) if num_reservations is None \
        else int(num_reservations)
    resv_owner = np.full((p,), -1, np.int32)
    if v:
        reqs = np.asarray(pods.requests)
        slot_free = np.zeros((reqs.shape[1],), np.float32)
        slot_free[CPU], slot_free[MEM] = RESV_SLOT_CPU, RESV_SLOT_MEM
        fits_slot = (reqs <= slot_free[None, :]).all(axis=1)
        plain = np.flatnonzero(
            fits_slot & ~np.asarray(deviceshare.has_device_request(pods))
            & ~numa_single)
        owners = rng.choice(plain, min(2 * v, plain.size),
                            replace=False)
        resv_owner[owners] = (np.arange(owners.size) % v).astype(
            np.int32)

    return pods.replace(
        numa_single=numa_single,
        reservation_owner=resv_owner,
        toleration_id=toleration_id, tol_forbid=tol_forbid,
        tol_prefer=tol_prefer,
        spread_id=spread_id, spread_carrier=spread_carrier,
        spread_member=spread_member,
        spread_max_skew=spread_max_skew,
        spread_domain=spread_domain, spread_count0=spread_count0,
        spread_dvalid=spread_dvalid,
        anti_id=anti_id, anti_member=anti_member,
        anti_carrier=anti_carrier, anti_domain=anti_domain,
        anti_count0=anti_count0,
        anti_carrier_count0=anti_carrier_count0,
        aff_id=aff_id, aff_carrier=aff_carrier, aff_member=aff_member,
        aff_domain=aff_domain, aff_count0=aff_count0,
        has_taints=True, has_spread=True, has_anti=True, has_aff=True)


def dom_classes(pods: PodBatch) -> tuple:
    """Static domain-class partition for core.schedule_batch: groups
    whose domain-matrix rows are byte-identical (the upstream
    topologyKey determines the row, so zone-keyed groups share one row
    shape and hostname-keyed groups another) share an in-step
    same-domain mask. Derived from the ACTUAL rows, so the contract
    (identical rows within a class) holds by construction."""
    def classes(dom):
        dom = np.asarray(dom)
        seen = {}
        for g in range(dom.shape[0]):
            seen.setdefault(dom[g].tobytes(), []).append(g)
        return tuple(tuple(v) for v in seen.values())
    return (classes(pods.spread_domain), classes(pods.anti_domain),
            classes(pods.aff_domain))


def topo_constrained_mask(pods: PodBatch) -> np.ndarray:
    """bool[P]: pods carrying or matching ANY spread/anti/aff term —
    the rows core.schedule_batch's `topo_prefix` contract requires at
    the front of each chunk."""
    p = pods.valid.shape[0]
    constrained = np.zeros((p,), bool)
    for f in ("spread_member", "spread_carrier", "anti_member",
              "anti_carrier", "aff_member", "aff_carrier"):
        m = np.asarray(getattr(pods, f))
        if m.shape[0] == p:
            constrained |= m.any(axis=1)
    return constrained


def pack_topo_prefix(pods: PodBatch, chunk: int,
                     align: int = 128) -> tuple:
    """Topology-class view of pack_gate_prefixes (one packing
    mechanism, one contract implementation): returns
    `(packed_pods, topo_prefix, constrained_mask)` satisfying
    core.schedule_batch's topo_prefix packing contract.

    On constraint-sparse workloads (the upstream norm: most pods carry
    no inter-pod term) this shrinks the scheduler's in-step same-domain
    [P, P] machinery to [prefix, prefix] — quadratic savings for the
    price of a stable in-chunk reorder. Queue semantics are unaffected:
    schedule_batch ranks by (priority desc, index asc), so the reorder
    only permutes tie-breaks among equal-priority pods, exactly like
    any other arrival order of the same queue. The returned mask is in
    PACKED order (the bench tail uses it to keep retry batches inside
    the contract)."""
    packed, prefixes, masks = pack_gate_prefixes(pods, chunk,
                                                 align=align)
    return packed, prefixes["topo"], masks["topo"]


def pack_gate_prefixes(pods: PodBatch, chunk: int,
                       align: int = 128) -> tuple:
    """Pack THREE gate classes into nested chunk prefixes and return
    `(packed_pods, prefixes, masks)` with `prefixes`/`masks` dicts
    keyed "topo" / "numa" / "gpu" satisfying the corresponding
    schedule_batch packing contracts (topo_prefix / numa_prefix /
    gpu_prefix).

    Pods sort within each chunk by (topo, numa, gpu) descending
    membership (stable), giving segment order [T..][N..][G..][rest]:
    every topo pod precedes every non-topo pod, every numa pod every
    (non-topo, non-numa) pod, and so on — so the three prefixes nest
    (topo <= numa <= gpu) and each class is fully covered by its own
    prefix. Classes: topo = any spread/anti/aff term (the
    topo_constrained_mask), numa = CPU-bind (numa_single), gpu = any
    device request (deviceshare.has_device_request). The numa_prefix
    contract ALSO requires a policy-free snapshot — that part is the
    caller's to assert (bench does), since the packer never sees
    nodes."""
    from koordinator_tpu.scheduler.plugins import deviceshare

    p = pods.valid.shape[0]
    if p % chunk:
        raise ValueError(f"{p} pods not divisible by chunk {chunk}")
    topo = topo_constrained_mask(pods)
    numa = np.asarray(pods.numa_single, bool)
    gpu = np.asarray(deviceshare.has_device_request(pods), bool)
    perm = np.empty((p,), np.int64)
    worst = {"topo": 0, "numa": 0, "gpu": 0}
    for s in range(0, p, chunk):
        t = topo[s:s + chunk]
        n = t | numa[s:s + chunk]
        g = n | gpu[s:s + chunk]
        # lexsort: last key is primary; stable within equal keys
        perm[s:s + chunk] = s + np.lexsort((~g, ~n, ~t))
        worst["topo"] = max(worst["topo"], int(t.sum()))
        worst["numa"] = max(worst["numa"], int(n.sum()))
        worst["gpu"] = max(worst["gpu"], int(g.sum()))
    prefixes = {k: min(-(-v // align) * align, chunk)
                for k, v in worst.items()}
    packed = pods.replace(**{f: np.asarray(getattr(pods, f))[perm]
                             for f in PER_POD_FIELDS})
    masks = {"topo": topo[perm], "numa": numa[perm], "gpu": gpu[perm],
             # the applied permutation: packed[i] == pods[perm[i]], so
             # original_row = perm[packed_row]; callers mapping per-pod
             # RESULTS back to the caller's order index with the
             # INVERSE permutation (inv[perm] = arange; the service
             # path does exactly this)
             "perm": perm}
    # the contracts the scheduler relies on (real raises: silent
    # miscomputation on violation, so -O must not strip these)
    for key in ("topo", "numa", "gpu"):
        m, pref = masks[key], prefixes[key]
        for s in range(0, p, chunk):
            if m[s + pref:s + chunk].any():
                raise ValueError(
                    f"pack_gate_prefixes: {key} pod escaped its prefix")
    return packed, prefixes, masks


def stack_pod_chunks(pods: PodBatch, chunk: int) -> dict:
    """[P, ...] per-pod columns -> [C, CHUNK, ...] scan operands (the
    bench sweep shape; zero-copy reshape of the contiguous batch). Shared
    by bench.py and bench_configs.py so the two harnesses cannot drift."""
    num = pods.valid.shape[0]
    if num % chunk:
        raise ValueError(f"{num} pods not divisible by chunk {chunk}")
    n_chunks = num // chunk
    return {f: getattr(pods, f).reshape(n_chunks, chunk,
                                        *getattr(pods, f).shape[1:])
            for f in PER_POD_FIELDS}


# re-exported from the schema (which owns the per-pod column list) so
# existing callers keep importing it from here
PER_POD_FIELDS = _PER_POD_FIELDS


def slice_batch(batch: PodBatch, start: int, size: int) -> PodBatch:
    """Static-size pod-chunk view (selector_match is batch-global)."""
    return batch.replace(**{f: getattr(batch, f)[start:start + size]
                            for f in PER_POD_FIELDS})
