"""Shared scaffolding for the in-process HTTP endpoints (scheduler
services/metrics, koordlet audit query): a quiet JSON request handler
base and a background ThreadingHTTPServer wrapper, so each endpoint only
writes its routes."""

from __future__ import annotations

import http.server
import json
import threading


class QuietJsonHandler(http.server.BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler with stderr logging silenced and JSON/raw
    reply helpers."""

    def log_message(self, *args) -> None:  # quiet
        pass

    def reply_json(self, code: int, payload: dict) -> None:
        self.reply_raw(code, "application/json",
                       json.dumps(payload).encode())

    def reply_raw(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class BackgroundHTTPServer:
    """ThreadingHTTPServer on a daemon thread; `port` reflects the bound
    (possibly ephemeral) port."""

    def __init__(self, handler_cls, host: str = "127.0.0.1", port: int = 0):
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class MetricsServer:
    """A minimal /metrics exposition endpoint over a Registry — the
    per-daemon Prometheus scrape surface (the reference's koordlet/
    manager/descheduler each serve client_golang's promhttp handler)."""

    def __init__(self, registry, host: str = "0.0.0.0", port: int = 0):
        registry_ref = registry

        class Handler(QuietJsonHandler):
            def do_GET(self):
                if self.path.startswith("/metrics"):
                    self.reply_raw(200, "text/plain; version=0.0.4",
                                   registry_ref.expose().encode("utf-8"))
                    return
                if self.path.startswith("/healthz"):
                    self.reply_json(200, {"ok": True})
                    return
                self.reply_json(404, {"error": "not found"})

        self._server = BackgroundHTTPServer(Handler, host, port)
        self.port = self._server.port

    def close(self) -> None:
        self._server.close()
