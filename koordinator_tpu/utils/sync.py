"""koordrace contracts: declared guarded-by tables for concurrent state.

The fourth rung of the contract ladder (shape -> dtype -> pad -> race).
`@guarded_by(...)` declares, per class, which lock guards each mutable
attribute — the same move `@shape_contract` makes for kernel shapes: a
zero-cost literal table that two independent tiers check.

  Tier A: tools/lint/analyzers/race.py walks the AST against these
          tables (GB001 access outside the lock, GB002 check-then-act,
          GB003 escaping references, GB004 declared-vs-actual drift,
          GB005 malformed contracts).
  Tier B: tools/racecheck.py drives seeded deterministic interleavings
          over the real classes and asserts their invariants hold.

Contract vocabulary (every guard value is a literal string — the static
tier never evaluates code):

  "_lock"            the instance attribute naming the guarding lock;
                     every read/write of the field must happen inside a
                     `with self._lock:` block (helper methods are
                     resolved through the intra-class call graph)
  "publish-once"     assigned in __init__ (or before threads start) and
                     never rebound after publication; readers need no
                     lock because writers no longer exist
  "confined"         touched by exactly one thread for the object's
                     whole life (per-cycle scheduler machinery,
                     threading.local handles) — confinement IS the lock
  "racy-monitor"     deliberately unsynchronized monitoring state
                     (last_* observability attrs): torn reads are
                     tolerated by design and documented here rather
                     than silenced with pragmas
  "external:Owner.lock"
                     guarded by ANOTHER object's lock — the journal's
                     records are mutated only under the owning
                     SchedulerService's commit lock; the class itself
                     deliberately owns no lock

The decorator costs nothing at runtime beyond one dict insert at import
time: no wrappers, no per-access checks, no __slots__ games. Duplicate
registration raises — two contracts for one class means one is stale.
"""

from __future__ import annotations

import re
from typing import Dict

# dotted class name ("koordinator_tpu.snapshot.store.SnapshotStore")
# -> {attr: guard}. Populated at import time by @guarded_by.
GUARDED_BY: Dict[str, Dict[str, str]] = {}

# module name -> {global_name: guard} for module-level locks (the
# compilecache counters pattern). Guard grammar is the subset that
# makes sense at module scope: a module-global lock name.
MODULE_GUARDS: Dict[str, Dict[str, str]] = {}

# the non-lock guard keywords; anything else must be an attribute name
# (a lock the class owns) or an external:Owner.lock reference
GUARD_VOCAB = ("publish-once", "confined", "racy-monitor")

_IDENT = re.compile(r"^[A-Za-z_]\w*$")
_EXTERNAL = re.compile(r"^external:[A-Za-z_]\w*(\.[A-Za-z_]\w*)+$")


def _validate(owner: str, table: Dict[str, str]) -> None:
    if not table:
        raise ValueError(f"guarded_by on {owner}: empty contract — a "
                         f"lock-owning class must declare its fields")
    for attr, guard in table.items():
        if not isinstance(attr, str) or not _IDENT.match(attr):
            raise ValueError(f"guarded_by on {owner}: field name "
                             f"{attr!r} is not an identifier")
        if not isinstance(guard, str):
            raise ValueError(f"guarded_by on {owner}: guard for "
                             f"{attr!r} must be a literal string, got "
                             f"{type(guard).__name__}")
        if guard in GUARD_VOCAB:
            continue
        if guard.startswith("external:"):
            if not _EXTERNAL.match(guard):
                raise ValueError(
                    f"guarded_by on {owner}: malformed external guard "
                    f"{guard!r} for {attr!r} (want "
                    f"'external:Owner.lock_attr')")
            continue
        if not _IDENT.match(guard):
            raise ValueError(f"guarded_by on {owner}: guard {guard!r} "
                             f"for {attr!r} is neither a lock "
                             f"attribute name nor one of {GUARD_VOCAB}")


def guarded_by(**table: str):
    """Class decorator: register the class's concurrency contract.

    Keyword names are instance attributes; values are guards per the
    module docstring's vocabulary. The table is validated and frozen at
    decoration time; the class itself is returned untouched.
    """

    def deco(cls: type) -> type:
        name = getattr(cls, "__name__", None)
        module = getattr(cls, "__module__", None)
        if not name or not module:
            raise ValueError("guarded_by target has no name/module")
        key = f"{module}.{name}"
        _validate(key, table)
        if key in GUARDED_BY:
            raise ValueError(f"duplicate guarded_by contract {key}")
        GUARDED_BY[key] = dict(table)
        return cls

    return deco


def guard_module(module: str, **table: str) -> None:
    """Declare guards for MODULE-LEVEL mutable globals (the
    compilecache counters pattern: one module lock, a few dicts).
    Call as `guard_module(__name__, _counts="_lock", ...)` next to the
    globals it describes. Guards follow the same vocabulary as
    guarded_by; lock names refer to module globals."""
    if not isinstance(module, str) or not module:
        raise ValueError("guard_module: module name required "
                         "(pass __name__)")
    _validate(module, table)
    if module in MODULE_GUARDS:
        raise ValueError(f"duplicate guard_module contract {module}")
    MODULE_GUARDS[module] = dict(table)
