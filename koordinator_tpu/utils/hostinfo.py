"""Host fingerprint for benchmark/latency artifacts.

The CI hosts this project runs on live-migrate and resize mid-session
(observed: nproc 8 -> 1 between rounds).  Every emitted bench line carries
these fields so a degraded-host number can be told apart from a kernel
regression when comparing artifacts across rounds (the reference leans on
stable dedicated hosts for its Go microbenchmarks and records nothing —
pkg/scheduler/plugins/reservation/transformer_benchmark_test.go — so this
is a deliberate addition, not a parity item).
"""

import os
import platform


def host_fields() -> dict:
    return {"cores": os.cpu_count() or 0, "host": platform.node()}
