"""Field-name conventions shared by every JSON-config surface."""

from __future__ import annotations

import re

_SNAKE_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])")


def camel_to_snake(key: str) -> str:
    """cpuEvictBEUsageThresholdPercent -> cpu_evict_be_usage_threshold_
    percent: acronym runs (BE, CPU) stay one segment — a per-character
    split would mangle them into b_e."""
    return _SNAKE_RE.sub("_", key).lower()
