"""Bind-time materialization: turn a ScheduleResult row into the pod
annotations the node agent's runtime hooks consume.

Mirrors the PreBind writes of the reference plugins (SURVEY.md 3.1):
- NodeNUMAResource writes `scheduling.koordinator.sh/resource-status`
  (zone + exact cpuset, plugin.go:427-463) — the cpuset comes from the
  host-side accumulator (cpu_accumulator.take_cpus) on the chosen node's
  topology, exactly like the reference runs takeCPUs at Reserve time.
- DeviceShare writes the device-allocation annotation (minors + per-
  instance shares); PCIe-grouped minors are ordered so joint-allocate
  consumers enumerate devices on the same root first.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from koordinator_tpu.api.extension import ANNOTATION_RESOURCE_STATUS
from koordinator_tpu.koordlet.runtimehooks import ANNOTATION_DEVICE_ALLOCATED
from koordinator_tpu.scheduler.plugins.cpu_accumulator import (
    CPUTopology,
    take_cpus,
)
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch


def resource_status_annotation(result, pod_index: int,
                               topology: Optional[CPUTopology] = None,
                               cpus_needed: int = 0,
                               allocated: Optional[Dict[int, int]] = None,
                               bind_policy: str = "FullPCPUs") -> Dict[str, str]:
    """The resource-status annotation for a NUMA-bound pod; {} when the pod
    took no zone. With a topology, the exact cpuset is accumulated on the
    chosen zone (otherwise only the zone is reported)."""
    zone = int(np.asarray(result.numa_zone)[pod_index])
    if zone < 0:
        return {}
    status: Dict[str, object] = {"numaNodes": [zone]}
    if topology is not None and cpus_needed > 0:
        available = {c.cpu for c in topology.nodes.get(zone, ())}
        cpus = take_cpus(topology, available, allocated or {}, cpus_needed,
                         bind_policy=bind_policy)
        status["cpuset"] = ",".join(str(c) for c in sorted(cpus))
    return {ANNOTATION_RESOURCE_STATUS: json.dumps(status)}


def resize_reserve_pod(snap: ClusterSnapshot, pods: PodBatch, result,
                       pod_index: int, reservation, gate=None) -> bool:
    """ResizePod: after Reserve, rewrite a placed RESERVE pod's resource
    spec to the CONCRETE device allocation, so the Reservation's
    allocatable reflects what was actually taken on the chosen node —
    notably a gpu-memory-ratio request becomes exact gpu-memory for that
    node's GPU model (frameworkext interface.go:176-180 ResizePodPlugin;
    deviceshare plugin.go:461-481; gated by scheduler_features.go:59).
    Returns True when the reservation's requests were rewritten."""
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.features import DEFAULT_FEATURE_GATE
    from koordinator_tpu.scheduler.plugins import deviceshare

    gate = gate if gate is not None else DEFAULT_FEATURE_GATE
    if not gate.enabled("ResizePod"):
        return False
    if int(np.asarray(result.assignment)[pod_index]) < 0:
        return False
    take = np.asarray(result.gpu_take)[pod_index]
    n_taken = int(take.sum())
    if n_taken == 0:
        return False
    _, per = deviceshare.per_instance_at(
        snap.devices, pods, np.asarray(result.assignment))
    per_row = np.asarray(per)[pod_index]
    from koordinator_tpu.snapshot.schema import DEV_CORE, DEV_MEM
    reservation.requests[RK.GPU_CORE] = float(per_row[DEV_CORE]) * n_taken
    reservation.requests[RK.GPU_MEMORY] = float(per_row[DEV_MEM]) * n_taken
    # the spec is now concrete: a ratio request no longer applies
    reservation.gpu_memory_ratio = 0.0
    return True


def device_allocation_annotation(snap: ClusterSnapshot, pods: PodBatch,
                                 result, pod_index: int) -> Dict[str, str]:
    """The device-allocation annotation from the result's instance masks;
    {} when the pod took no devices. GPU minors are sorted PCIe-group-
    first so same-root pairs stay adjacent (topology guide preference)."""
    take = np.asarray(result.gpu_take)[pod_index]
    aux = np.asarray(result.aux_inst)[pod_index]
    node = int(np.asarray(result.assignment)[pod_index])
    alloc: Dict[str, list] = {}
    if node >= 0 and take.any():
        pcie = np.asarray(snap.devices.gpu_pcie)[node]
        minors = sorted((int(m) for m in np.nonzero(take)[0]),
                        key=lambda m: (int(pcie[m]), m))
        alloc["gpu"] = [{"minor": m} for m in minors]
    for t, key in enumerate(("rdma", "fpga")):
        if node >= 0 and aux[t] >= 0:
            alloc[key] = [{"minor": int(aux[t])}]
    if not alloc:
        return {}
    return {ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc)}
