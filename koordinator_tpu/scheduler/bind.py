"""Bind-time materialization: turn a ScheduleResult row into the pod
annotations the node agent's runtime hooks consume.

Mirrors the PreBind writes of the reference plugins (SURVEY.md 3.1):
- NodeNUMAResource writes `scheduling.koordinator.sh/resource-status`
  (zone + exact cpuset, plugin.go:427-463) — the cpuset comes from the
  host-side accumulator (cpu_accumulator.take_cpus) on the chosen node's
  topology, exactly like the reference runs takeCPUs at Reserve time.
- DeviceShare writes the device-allocation annotation (minors + per-
  instance shares); PCIe-grouped minors are ordered so joint-allocate
  consumers enumerate devices on the same root first.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from koordinator_tpu.api.extension import ANNOTATION_RESOURCE_STATUS
from koordinator_tpu.koordlet.runtimehooks import ANNOTATION_DEVICE_ALLOCATED
from koordinator_tpu.scheduler.plugins.cpu_accumulator import (
    CPUTopology,
    take_cpus,
)
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch


def resource_status_annotation(result, pod_index: int,
                               topology: Optional[CPUTopology] = None,
                               cpus_needed: int = 0,
                               allocated: Optional[Dict[int, int]] = None,
                               bind_policy: str = "FullPCPUs") -> Dict[str, str]:
    """The resource-status annotation for a NUMA-bound pod; {} when the pod
    took no zone. With a topology, the exact cpuset is accumulated on the
    chosen zone (otherwise only the zone is reported)."""
    zone = int(np.asarray(result.numa_zone)[pod_index])
    if zone < 0:
        return {}
    status: Dict[str, object] = {"numaNodes": [zone]}
    if topology is not None and cpus_needed > 0:
        available = {c.cpu for c in topology.nodes.get(zone, ())}
        cpus = take_cpus(topology, available, allocated or {}, cpus_needed,
                         bind_policy=bind_policy)
        status["cpuset"] = ",".join(str(c) for c in sorted(cpus))
    return {ANNOTATION_RESOURCE_STATUS: json.dumps(status)}


def device_allocation_annotation(snap: ClusterSnapshot, pods: PodBatch,
                                 result, pod_index: int) -> Dict[str, str]:
    """The device-allocation annotation from the result's instance masks;
    {} when the pod took no devices. GPU minors are sorted PCIe-group-
    first so same-root pairs stay adjacent (topology guide preference)."""
    take = np.asarray(result.gpu_take)[pod_index]
    aux = np.asarray(result.aux_inst)[pod_index]
    node = int(np.asarray(result.assignment)[pod_index])
    alloc: Dict[str, list] = {}
    if node >= 0 and take.any():
        pcie = np.asarray(snap.devices.gpu_pcie)[node]
        minors = sorted((int(m) for m in np.nonzero(take)[0]),
                        key=lambda m: (int(pcie[m]), m))
        alloc["gpu"] = [{"minor": m} for m in minors]
    for t, key in enumerate(("rdma", "fpga")):
        if node >= 0 and aux[t] >= 0:
            alloc[key] = [{"minor": int(aux[t])}]
    if not alloc:
        return {}
    return {ANNOTATION_DEVICE_ALLOCATED: json.dumps(alloc)}
