"""Typed scheduler plugin args with defaulting, validation, and conversion
to the device-kernel configs.

Capability parity with pkg/scheduler/apis/config (SURVEY.md 2.1
"scheduler apis/config", types.go:30-214 + v1beta2 defaults + validation):
each plugin's arguments are a typed object; `validate()` rejects
out-of-range values; `schedule_options()` lowers the whole profile into
the static/traced arguments of scheduler.core.schedule_batch plus the
LoadAwareConfig operand.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.api.extension import ResourceKind
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.snapshot.schema import AGG_TYPES

MostAllocated = "MostAllocated"
LeastAllocated = "LeastAllocated"
_STRATEGIES = (MostAllocated, LeastAllocated)


def _validate_percent_map(name: str, m: Dict[ResourceKind, float],
                          errs: List[str], max_value: float = 100.0) -> None:
    for kind, v in m.items():
        if not 0 <= v <= max_value:
            errs.append(f"{name}[{kind.name}]={v} outside [0, {max_value}]")


@dataclasses.dataclass
class LoadAwareSchedulingArgs:
    """types.go:30-58 with v1beta2 defaults."""

    node_metric_expiration_seconds: float = 180.0
    resource_weights: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 1.0,
                                 ResourceKind.MEMORY: 1.0})
    usage_thresholds: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 65.0,
                                 ResourceKind.MEMORY: 95.0})
    prod_usage_thresholds: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=dict)
    score_according_prod_usage: bool = False
    estimated_scaling_factors: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=lambda: {ResourceKind.CPU: 85.0,
                                 ResourceKind.MEMORY: 70.0})
    # aggregated percentile profile (LoadAwareSchedulingAggregatedArgs)
    agg_usage_thresholds: Dict[ResourceKind, float] = dataclasses.field(
        default_factory=dict)
    filter_agg_type: str = ""
    score_agg_type: str = ""

    def validate(self) -> List[str]:
        errs: List[str] = []
        if self.node_metric_expiration_seconds <= 0:
            errs.append("nodeMetricExpirationSeconds must be positive")
        for kind, w in self.resource_weights.items():
            if w < 0:
                errs.append(f"resourceWeights[{kind.name}] must be >= 0")
        _validate_percent_map("usageThresholds", self.usage_thresholds, errs)
        _validate_percent_map("prodUsageThresholds",
                              self.prod_usage_thresholds, errs)
        _validate_percent_map("aggregatedUsageThresholds",
                              self.agg_usage_thresholds, errs)
        for kind, f in self.estimated_scaling_factors.items():
            if not 0 < f <= 100:
                errs.append(
                    f"estimatedScalingFactors[{kind.name}] outside (0, 100]")
        for label, agg in (("usageAggregationType", self.filter_agg_type),
                           ("scoreAggregationType", self.score_agg_type)):
            if agg and agg not in AGG_TYPES:
                errs.append(f"{label}={agg!r} not one of {AGG_TYPES}")
        return errs

    def to_config(self) -> LoadAwareConfig:
        return LoadAwareConfig.make(
            resource_weights=self.resource_weights,
            usage_thresholds=self.usage_thresholds,
            prod_usage_thresholds=self.prod_usage_thresholds or None,
            agg_usage_thresholds=self.agg_usage_thresholds or None,
            filter_agg_type=self.filter_agg_type,
            score_agg_type=self.score_agg_type,
            score_according_prod_usage=self.score_according_prod_usage)


@dataclasses.dataclass
class NodeNUMAResourceArgs:
    """types.go:103-115."""

    default_cpu_bind_policy: str = ""   # "", FullPCPUs, SpreadByPCPUs
    numa_scoring_strategy: str = MostAllocated
    scoring_strategy: str = LeastAllocated

    def validate(self) -> List[str]:
        errs: List[str] = []
        if self.default_cpu_bind_policy not in ("", "FullPCPUs",
                                                "SpreadByPCPUs"):
            errs.append(f"defaultCPUBindPolicy="
                        f"{self.default_cpu_bind_policy!r} invalid")
        for label, s in (("numaScoringStrategy", self.numa_scoring_strategy),
                         ("scoringStrategy", self.scoring_strategy)):
            if s not in _STRATEGIES:
                errs.append(f"{label}={s!r} not one of {_STRATEGIES}")
        return errs


@dataclasses.dataclass
class ReservationArgs:
    """types.go:156-162."""

    enable_preemption: bool = False

    def validate(self) -> List[str]:
        return []


@dataclasses.dataclass
class ElasticQuotaArgs:
    """types.go:166-195."""

    delay_evict_time_seconds: float = 300.0
    revoke_pod_interval_seconds: float = 60.0
    monitor_all_quotas: bool = False
    enable_check_parent_quota: bool = False
    enable_runtime_quota: bool = True

    def validate(self) -> List[str]:
        errs: List[str] = []
        if self.delay_evict_time_seconds < 0:
            errs.append("delayEvictTime must be >= 0")
        if self.revoke_pod_interval_seconds <= 0:
            errs.append("revokePodInterval must be positive")
        return errs


@dataclasses.dataclass
class CoschedulingArgs:
    """types.go:197-210."""

    default_timeout_seconds: float = 600.0
    controller_workers: int = 1
    skip_check_schedule_cycle: bool = False

    def validate(self) -> List[str]:
        errs: List[str] = []
        if self.default_timeout_seconds <= 0:
            errs.append("defaultTimeout must be positive")
        if self.controller_workers < 1:
            errs.append("controllerWorkers must be >= 1")
        return errs


@dataclasses.dataclass
class DeviceShareArgs:
    """types.go:214-222."""

    scoring_strategy: str = LeastAllocated

    def validate(self) -> List[str]:
        if self.scoring_strategy not in _STRATEGIES:
            return [f"scoringStrategy={self.scoring_strategy!r} not one of "
                    f"{_STRATEGIES}"]
        return []


@dataclasses.dataclass
class SchedulerProfile:
    """The full plugin-args profile, lowered into schedule_batch inputs."""

    load_aware: LoadAwareSchedulingArgs = dataclasses.field(
        default_factory=LoadAwareSchedulingArgs)
    numa: NodeNUMAResourceArgs = dataclasses.field(
        default_factory=NodeNUMAResourceArgs)
    reservation: ReservationArgs = dataclasses.field(
        default_factory=ReservationArgs)
    elastic_quota: ElasticQuotaArgs = dataclasses.field(
        default_factory=ElasticQuotaArgs)
    coscheduling: CoschedulingArgs = dataclasses.field(
        default_factory=CoschedulingArgs)
    device_share: DeviceShareArgs = dataclasses.field(
        default_factory=DeviceShareArgs)

    def validate(self) -> List[str]:
        errs: List[str] = []
        for name, args in (("loadAware", self.load_aware),
                           ("nodeNUMAResource", self.numa),
                           ("reservation", self.reservation),
                           ("elasticQuota", self.elastic_quota),
                           ("coscheduling", self.coscheduling),
                           ("deviceShare", self.device_share)):
            errs.extend(f"{name}: {e}" for e in args.validate())
        return errs

    def schedule_options(self) -> Dict[str, object]:
        """kwargs for scheduler.core.schedule_batch (static args) — the
        LoadAwareConfig operand rides separately via `load_aware_config`."""
        errs = self.validate()
        if errs:
            raise ValueError("; ".join(errs))
        strategy = ("most" if self.numa.numa_scoring_strategy == MostAllocated
                    else "least")
        return {
            "numa_strategy": strategy,
            "device_strategy": ("most" if self.device_share.scoring_strategy
                                == MostAllocated else "least"),
        }

    def load_aware_config(self) -> LoadAwareConfig:
        return self.load_aware.to_config()
