"""Scheduling error-handler chain + reservation unschedulable writeback.

Capability parity with `pkg/scheduler/frameworkext/errorhandler_dispatcher.go`
(pre filters -> default handler -> post filters, a filter returning True
claims the error) and `frameworkext/eventhandlers/reservation_handler.go`
(reserve-pod failures write a Scheduled=False/Unschedulable condition on
the Reservation and requeue it unless it already landed on a node).

In the batched TPU scheduler a "scheduling error" is an unplaced row of a
batch (assignment -1): `dispatch_batch_errors` fans the unplaced pods out
through the chain, so plugins observe exactly the per-pod error stream
the reference's queue-centric scheduler produces.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

import numpy as np

from koordinator_tpu.api import types as api


@dataclasses.dataclass
class SchedulingError(Exception):
    """FitError equivalent: the pod failed this cycle."""

    message: str = "no fit"
    unschedulable: bool = True  # False = infrastructure error, retry hard

    def __str__(self) -> str:
        return self.message


@dataclasses.dataclass
class QueuedPodInfo:
    """What the handlers see per failed pod (framework.QueuedPodInfo's
    relevant surface): the typed pod, plus attempt bookkeeping."""

    pod: api.Pod
    attempts: int = 1
    unschedulable_plugins: List[str] = dataclasses.field(default_factory=list)


# a filter returns True to CLAIM the error (stop the chain)
ErrorFilter = Callable[[QueuedPodInfo, SchedulingError], bool]
ErrorHandler = Callable[[QueuedPodInfo, SchedulingError], None]


class ErrorHandlerDispatcher:
    """errorhandler_dispatcher.go: pre filters may claim; otherwise the
    default handler runs; post filters always get a chance afterwards."""

    def __init__(self, default_handler: Optional[ErrorHandler] = None):
        self._pre: List[ErrorFilter] = []
        self._post: List[ErrorFilter] = []
        self._default: Optional[ErrorHandler] = default_handler

    def set_default_handler(self, handler: ErrorHandler) -> None:
        self._default = handler

    def register(self, pre: Optional[ErrorFilter] = None,
                 post: Optional[ErrorFilter] = None) -> None:
        if pre is not None:
            self._pre.append(pre)
        if post is not None:
            self._post.append(post)

    def error(self, pod_info: QueuedPodInfo, err: SchedulingError) -> None:
        try:
            for f in self._pre:
                if f(pod_info, err):
                    return
            if self._default is not None:
                self._default(pod_info, err)
        finally:
            for f in self._post:
                if f(pod_info, err):
                    break


def make_preemption_post_filter(
        get_nodes: Callable[[], List[api.Node]],
        get_pods_by_node: Callable[[], dict],
        on_nominate: Callable,
        get_devices: Optional[Callable[[], dict]] = None) -> ErrorFilter:
    """The default-preemption PostFilter as an error-chain post filter:
    an unschedulable pod with a priority dry-runs the cluster view for a
    minimal victim set (scheduler/preemption.py); `on_nominate(pod,
    nomination)` receives the winner — the caller evicts the victims and
    requeues the pod against the next snapshot (the nominatedNodeName
    handshake). Returns True when a nomination was made so later post
    filters can skip."""
    from koordinator_tpu.scheduler.preemption import find_preemption

    def post(pod_info: QueuedPodInfo, err: SchedulingError) -> bool:
        pod = pod_info.pod
        # infrastructure errors retry as-is — never evict for them
        # (upstream's PostFilter runs only for Unschedulable status).
        # A priority of 0 is a legitimate preemptor against negative
        # (e.g. BE) victims — only a pod with NO priority at all skips;
        # select_victims_on_node's `< prio` comparison does the rest.
        if not err.unschedulable or pod.priority is None:
            return False
        nomination = find_preemption(
            pod, get_nodes(), get_pods_by_node(),
            devices=get_devices() if get_devices is not None else None)
        if nomination is None:
            return False
        on_nominate(pod, nomination)
        return True

    return post


def set_reservation_unschedulable(r: api.Reservation, msg: str,
                                  now: Optional[float] = None) -> None:
    """setReservationUnschedulable (reservation_handler.go:155-190):
    append or refresh the Scheduled condition; an already-scheduled
    reservation only gets its probe time bumped (the condition records
    the LAST scheduling attempt, the phase is untouched so the reserve
    pod retries next cycle)."""
    now = time.time() if now is None else now
    for cond in r.conditions:
        if cond.type == "Scheduled":
            if cond.status == "True":
                cond.last_probe_time = now  # scheduled; just probed again
            else:
                cond.reason = api.REASON_RESERVATION_UNSCHEDULABLE
                cond.message = msg
                cond.last_probe_time = now
            return
    r.conditions.append(api.ReservationCondition(
        type="Scheduled", status="False",
        reason=api.REASON_RESERVATION_UNSCHEDULABLE, message=msg,
        last_probe_time=now, last_transition_time=now))


def set_reservation_scheduled(r: api.Reservation, node_name: str,
                              now: Optional[float] = None) -> None:
    """The success-side writeback the controllers run on assignment."""
    now = time.time() if now is None else now
    r.node_name = node_name
    for cond in r.conditions:
        if cond.type == "Scheduled":
            if cond.status != "True":
                cond.last_transition_time = now
            cond.status = "True"
            cond.reason = api.REASON_RESERVATION_SCHEDULED
            cond.message = ""
            cond.last_probe_time = now
            return
    r.conditions.append(api.ReservationCondition(
        type="Scheduled", status="True",
        reason=api.REASON_RESERVATION_SCHEDULED,
        last_probe_time=now, last_transition_time=now))


def make_reservation_error_filter(
        get_reservation: Callable[[str], Optional[api.Reservation]],
        requeue: Optional[Callable[[api.Reservation], None]] = None,
        clock: Callable[[], float] = time.time) -> ErrorFilter:
    """The reservation pre-filter (reservation_handler.go:60-151): claims
    reserve-pod errors, writes the unschedulable condition, and requeues
    the reservation for the next cycle — unless the live object already
    carries a node (bind raced the error), where it aborts the requeue."""

    def filt(pod_info: QueuedPodInfo, err: SchedulingError) -> bool:
        name = reservation_name_of(pod_info.pod)
        if name is None:
            return False  # not a reserve pod: let the default handler run
        r = get_reservation(name)
        if r is None:
            return True  # reservation deleted; drop silently (":77-80")
        if r.node_name:
            return True  # already landed; stale error (":136-141")
        set_reservation_unschedulable(r, str(err), clock())
        if requeue is not None:
            requeue(r)
        return True

    return filt


# reserve pods are synthesized from reservations; the marker label is the
# TPU build's equivalent of reservationutil.IsReservePod's name scheme
LABEL_RESERVE_POD = "koordinator.sh/reservation-name"


def reservation_name_of(pod: api.Pod) -> Optional[str]:
    return pod.meta.labels.get(LABEL_RESERVE_POD)


def reserve_pod_for(r: api.Reservation) -> api.Pod:
    """NewReservePod: the pod the scheduler places to site a reservation."""
    return api.Pod(
        meta=api.ObjectMeta(
            name=f"reserve-{r.meta.name}", uid=f"reserve-{r.meta.uid}",
            labels={LABEL_RESERVE_POD: r.meta.name}),
        requests=dict(r.requests))


def dispatch_batch_errors(dispatcher: ErrorHandlerDispatcher,
                          assignment: np.ndarray, valid: np.ndarray,
                          pods: List[api.Pod],
                          message: str = "no node fits") -> int:
    """Fan a batch's unplaced rows through the chain; returns the count.
    `pods` is the typed pod list in batch order (rows past its length are
    padding and never dispatched)."""
    n = 0
    for i, pod in enumerate(pods):
        if i >= assignment.shape[0] or not bool(valid[i]):
            continue
        if int(assignment[i]) >= 0:
            continue
        dispatcher.error(QueuedPodInfo(pod=pod),
                        SchedulingError(f"{message}: pod "
                                        f"{pod.meta.namespaced_name}"))
        n += 1
    return n
