"""Scheduling error-handler chain, typed runtime-failure classification,
and the reservation unschedulable writeback.

Capability parity with `pkg/scheduler/frameworkext/errorhandler_dispatcher.go`
(pre filters -> default handler -> post filters, a filter returning True
claims the error) and `frameworkext/eventhandlers/reservation_handler.go`
(reserve-pod failures write a Scheduled=False/Unschedulable condition on
the Reservation and requeue it unless it already landed on a node).

In the batched TPU scheduler a "scheduling error" is an unplaced row of a
batch (assignment -1): `dispatch_batch_errors` fans the unplaced pods out
through the chain, so plugins observe exactly the per-pod error stream
the reference's queue-centric scheduler produces.

This module also owns the RUNTIME failure model of the resident service
(docs/DESIGN.md "Failure model & degradation ladder"): `classify_failure`
maps any exception a device-program call can raise into a `FailureClass`,
and `Backoff` is the bounded-retry bookkeeping the SchedulerService (and
any other retry site) uses between attempts. Every hot-path `except
Exception` around a device-program call must route through the
classifier — koordlint RB001 enforces it.
"""

from __future__ import annotations

import dataclasses
import enum
import random
import time
from typing import Callable, List, Optional

import numpy as np

from koordinator_tpu.api import types as api


@dataclasses.dataclass
class SchedulingError(Exception):
    """FitError equivalent: the pod failed this cycle."""

    message: str = "no fit"
    unschedulable: bool = True  # False = infrastructure error, retry hard

    def __str__(self) -> str:
        return self.message


# --- typed runtime-failure classification ----------------------------------


class FailureClass(enum.Enum):
    """Every way a device-program cycle can fail, as ONE closed set: the
    degradation ladder, the retry policy, the chaos matrix, and the
    failure metrics all key on it, so a new failure mode must be named
    here before any component can react to it."""

    GUARD_TRIP = "guard_trip"                  # health guards quarantined input
    RESOURCE_EXHAUSTED = "resource_exhausted"  # XLA OOM / allocator failure
    DEVICE_LOST = "device_lost"                # device unreachable/halted
    XLA_INTERNAL = "xla_internal"              # compiler/runtime internal error
    WATCHDOG_STALL = "watchdog_stall"          # cycle exceeded the monitor budget
    UNKNOWN = "unknown"                        # anything unrecognized


# classes where retrying the SAME program may succeed (a lost device can
# reconnect, an internal error can be a transient runtime hiccup); OOM is
# deliberately NOT here — the identical program OOMs identically, so the
# only useful reaction is degrading (chunk halving), never a plain retry
TRANSIENT_CLASSES = frozenset({FailureClass.DEVICE_LOST,
                               FailureClass.XLA_INTERNAL,
                               FailureClass.UNKNOWN})


class GuardTripError(RuntimeError):
    """Raised by callers that treat a non-zero guard health word as fatal
    (strict mode); carries the packed word for the classifier/logs."""

    def __init__(self, word: int, message: str = ""):
        super().__init__(message or f"device health guard tripped: "
                                    f"word=0x{word:x}")
        self.word = int(word)


class WatchdogStall(RuntimeError):
    """A scheduling cycle exceeded the SchedulerMonitor budget."""


# message fragments (upper-cased match) per class, in PRECEDENCE order:
# OOM text often embeds "INTERNAL"-flavored detail, so it must win
_MESSAGE_RULES = (
    (FailureClass.RESOURCE_EXHAUSTED,
     ("RESOURCE_EXHAUSTED", "OUT OF MEMORY", "ALLOCATION FAILURE", "OOM")),
    (FailureClass.DEVICE_LOST,
     ("DEVICE_LOST", "DEVICE LOST", "UNAVAILABLE", "DEVICE HALTED",
      "FAILED TO CONNECT", "SOCKET CLOSED")),
    (FailureClass.XLA_INTERNAL, ("INTERNAL", "DATA_LOSS", "ABORTED")),
)


def classify_failure(exc: BaseException) -> FailureClass:
    """Map an exception from a device-program call to its FailureClass.

    Typed exceptions win; otherwise the XLA status-code vocabulary in the
    message decides (XlaRuntimeError carries the absl status name —
    RESOURCE_EXHAUSTED, UNAVAILABLE, INTERNAL — as a message prefix).
    Matching is by type NAME so the classifier stays importable where
    jax is broken or absent (the koordlint analyzers run stdlib-only)."""
    if isinstance(exc, GuardTripError):
        return FailureClass.GUARD_TRIP
    if isinstance(exc, (WatchdogStall, TimeoutError)):
        return FailureClass.WATCHDOG_STALL
    msg = str(exc).upper()
    for cls, fragments in _MESSAGE_RULES:
        if any(f in msg for f in fragments):
            return cls
    mro_names = {t.__name__ for t in type(exc).__mro__}
    if {"XlaRuntimeError", "JaxRuntimeError"} & mro_names:
        # an XLA runtime failure with an unrecognized status: internal
        return FailureClass.XLA_INTERNAL
    return FailureClass.UNKNOWN


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter for TRANSIENT failures."""

    max_attempts: int = 3       # attempts at one ladder state before degrading
    base_seconds: float = 0.05
    multiplier: float = 2.0
    max_seconds: float = 2.0
    jitter_frac: float = 0.25   # +/- fraction of the computed delay


class Backoff:
    """Attempt/backoff bookkeeping for one retry site.

    Clocked on `time.monotonic`, NEVER wall-clock: an NTP step or DST
    jump under `time.time()` can move the clock backwards mid-retry and
    produce a negative backoff window (an instant hot-loop retry storm —
    the exact failure the backoff exists to prevent). Delays are a pure
    function of the ATTEMPT COUNT (clock-free), and `remaining()` clamps
    at zero, so no clock behavior can yield a negative window; pinned by
    tests/test_degradation.py."""

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 clock: Callable[[], float] = time.monotonic,
                 seed: int = 0):
        self.policy = policy or RetryPolicy()
        self._clock = clock
        self._rng = random.Random(seed)
        self.attempts = 0
        self._not_before: Optional[float] = None

    def exhausted(self) -> bool:
        return self.attempts >= self.policy.max_attempts

    def next_delay(self) -> float:
        """Record an attempt and return the delay before the next one
        (>= 0 always; jittered so synchronized retries fan out)."""
        p = self.policy
        delay = min(p.base_seconds * (p.multiplier ** self.attempts),
                    p.max_seconds)
        delay *= 1.0 + p.jitter_frac * (self._rng.random() * 2.0 - 1.0)
        delay = max(delay, 0.0)
        self.attempts += 1
        self._not_before = self._clock() + delay
        return delay

    def remaining(self) -> float:
        """Seconds until the backoff window closes, clamped at zero."""
        if self._not_before is None:
            return 0.0
        return max(self._not_before - self._clock(), 0.0)

    def reset(self) -> None:
        self.attempts = 0
        self._not_before = None


@dataclasses.dataclass
class QueuedPodInfo:
    """What the handlers see per failed pod (framework.QueuedPodInfo's
    relevant surface): the typed pod, plus attempt bookkeeping."""

    pod: api.Pod
    attempts: int = 1
    unschedulable_plugins: List[str] = dataclasses.field(default_factory=list)


# a filter returns True to CLAIM the error (stop the chain)
ErrorFilter = Callable[[QueuedPodInfo, SchedulingError], bool]
ErrorHandler = Callable[[QueuedPodInfo, SchedulingError], None]


class ErrorHandlerDispatcher:
    """errorhandler_dispatcher.go: pre filters may claim; otherwise the
    default handler runs; post filters always get a chance afterwards."""

    def __init__(self, default_handler: Optional[ErrorHandler] = None):
        self._pre: List[ErrorFilter] = []
        self._post: List[ErrorFilter] = []
        self._default: Optional[ErrorHandler] = default_handler

    def set_default_handler(self, handler: ErrorHandler) -> None:
        self._default = handler

    def register(self, pre: Optional[ErrorFilter] = None,
                 post: Optional[ErrorFilter] = None) -> None:
        if pre is not None:
            self._pre.append(pre)
        if post is not None:
            self._post.append(post)

    def error(self, pod_info: QueuedPodInfo, err: SchedulingError) -> None:
        try:
            for f in self._pre:
                if f(pod_info, err):
                    return
            if self._default is not None:
                self._default(pod_info, err)
        finally:
            for f in self._post:
                if f(pod_info, err):
                    break


def make_preemption_post_filter(
        get_nodes: Callable[[], List[api.Node]],
        get_pods_by_node: Callable[[], dict],
        on_nominate: Callable,
        get_devices: Optional[Callable[[], dict]] = None) -> ErrorFilter:
    """The default-preemption PostFilter as an error-chain post filter:
    an unschedulable pod with a priority dry-runs the cluster view for a
    minimal victim set (scheduler/preemption.py); `on_nominate(pod,
    nomination)` receives the winner — the caller evicts the victims and
    requeues the pod against the next snapshot (the nominatedNodeName
    handshake). Returns True when a nomination was made so later post
    filters can skip."""
    from koordinator_tpu.scheduler.preemption import find_preemption

    def post(pod_info: QueuedPodInfo, err: SchedulingError) -> bool:
        pod = pod_info.pod
        # infrastructure errors retry as-is — never evict for them
        # (upstream's PostFilter runs only for Unschedulable status).
        # A priority of 0 is a legitimate preemptor against negative
        # (e.g. BE) victims — only a pod with NO priority at all skips;
        # select_victims_on_node's `< prio` comparison does the rest.
        if not err.unschedulable or pod.priority is None:
            return False
        nomination = find_preemption(
            pod, get_nodes(), get_pods_by_node(),
            devices=get_devices() if get_devices is not None else None)
        if nomination is None:
            return False
        on_nominate(pod, nomination)
        return True

    return post


def set_reservation_unschedulable(r: api.Reservation, msg: str,
                                  now: Optional[float] = None) -> None:
    """setReservationUnschedulable (reservation_handler.go:155-190):
    append or refresh the Scheduled condition; an already-scheduled
    reservation only gets its probe time bumped (the condition records
    the LAST scheduling attempt, the phase is untouched so the reserve
    pod retries next cycle)."""
    now = time.time() if now is None else now
    for cond in r.conditions:
        if cond.type == "Scheduled":
            if cond.status == "True":
                cond.last_probe_time = now  # scheduled; just probed again
            else:
                cond.reason = api.REASON_RESERVATION_UNSCHEDULABLE
                cond.message = msg
                cond.last_probe_time = now
            return
    r.conditions.append(api.ReservationCondition(
        type="Scheduled", status="False",
        reason=api.REASON_RESERVATION_UNSCHEDULABLE, message=msg,
        last_probe_time=now, last_transition_time=now))


def set_reservation_scheduled(r: api.Reservation, node_name: str,
                              now: Optional[float] = None) -> None:
    """The success-side writeback the controllers run on assignment."""
    now = time.time() if now is None else now
    r.node_name = node_name
    for cond in r.conditions:
        if cond.type == "Scheduled":
            if cond.status != "True":
                cond.last_transition_time = now
            cond.status = "True"
            cond.reason = api.REASON_RESERVATION_SCHEDULED
            cond.message = ""
            cond.last_probe_time = now
            return
    r.conditions.append(api.ReservationCondition(
        type="Scheduled", status="True",
        reason=api.REASON_RESERVATION_SCHEDULED,
        last_probe_time=now, last_transition_time=now))


def make_reservation_error_filter(
        get_reservation: Callable[[str], Optional[api.Reservation]],
        requeue: Optional[Callable[[api.Reservation], None]] = None,
        clock: Callable[[], float] = time.time) -> ErrorFilter:
    """The reservation pre-filter (reservation_handler.go:60-151): claims
    reserve-pod errors, writes the unschedulable condition, and requeues
    the reservation for the next cycle — unless the live object already
    carries a node (bind raced the error), where it aborts the requeue."""

    def filt(pod_info: QueuedPodInfo, err: SchedulingError) -> bool:
        name = reservation_name_of(pod_info.pod)
        if name is None:
            return False  # not a reserve pod: let the default handler run
        r = get_reservation(name)
        if r is None:
            return True  # reservation deleted; drop silently (":77-80")
        if r.node_name:
            return True  # already landed; stale error (":136-141")
        set_reservation_unschedulable(r, str(err), clock())
        if requeue is not None:
            requeue(r)
        return True

    return filt


# reserve pods are synthesized from reservations; the marker label is the
# TPU build's equivalent of reservationutil.IsReservePod's name scheme
LABEL_RESERVE_POD = "koordinator.sh/reservation-name"


def reservation_name_of(pod: api.Pod) -> Optional[str]:
    return pod.meta.labels.get(LABEL_RESERVE_POD)


def reserve_pod_for(r: api.Reservation) -> api.Pod:
    """NewReservePod: the pod the scheduler places to site a reservation."""
    return api.Pod(
        meta=api.ObjectMeta(
            name=f"reserve-{r.meta.name}", uid=f"reserve-{r.meta.uid}",
            labels={LABEL_RESERVE_POD: r.meta.name}),
        requests=dict(r.requests))


def dispatch_batch_errors(dispatcher: ErrorHandlerDispatcher,
                          assignment: np.ndarray, valid: np.ndarray,
                          pods: List[api.Pod],
                          message: str = "no node fits",
                          infra_mask: Optional[np.ndarray] = None) -> int:
    """Fan a batch's unplaced rows through the chain; returns the count.
    `pods` is the typed pod list in batch order (rows past its length are
    padding and never dispatched). Rows set in `infra_mask` (the guard
    quarantine mask) dispatch as INFRASTRUCTURE errors
    (unschedulable=False): the input row was corrupt, not the cluster
    full, so preemption must not fire for them and requeue retries hard
    against the next (healthy) snapshot."""
    n = 0
    for i, pod in enumerate(pods):
        if i >= assignment.shape[0] or not bool(valid[i]):
            continue
        if int(assignment[i]) >= 0:
            continue
        if infra_mask is not None and bool(infra_mask[i]):
            err = SchedulingError(
                f"quarantined input row: pod {pod.meta.namespaced_name}",
                unschedulable=False)
        else:
            err = SchedulingError(f"{message}: pod "
                                  f"{pod.meta.namespaced_name}")
        dispatcher.error(QueuedPodInfo(pod=pod), err)
        n += 1
    return n
