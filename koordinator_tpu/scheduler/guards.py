"""Device health guards: columnar defect scans fused into the batch
program, a packed health word, and per-node/per-pod quarantine masks.

Failure model (docs/DESIGN.md "Failure model & degradation ladder"): a
resident service ingesting arrivals from millions of users WILL see a
NaN metric column, a negative allocatable, an out-of-range domain index
— and one poisoned [P, N] matmul corrupts every placement in the batch
(NaN * 0 == NaN). Instead of trusting the edge, the batch program scans
its own inputs:

- `snapshot_health(snap)` scans the node columns: non-finite metric
  values, invalid (negative/non-finite) allocatable or requested,
  per-node overcommit (requested > allocatable + tol, the same invariant
  `core.overcommit_ok` asserts host-side), and inconsistent NUMA pools.
- `batch_health(snap, pods)` scans the pod batch: non-finite or
  negative requests/estimated, gang/quota/selector/toleration ids out
  of the snapshot's capacity range, and domain-matrix entries out of
  the count-surface range (which would mis-gate a whole constraint
  group through clipped gathers).
- `apply_quarantine` neutralizes what the scans found: bad nodes become
  `schedulable=False` with their float rows scrubbed (NaN/Inf -> 0,
  negatives clamped), bad pods become `valid=False` with their request
  rows scrubbed, and a domain row with out-of-range entries is scrubbed
  to -1 with its CARRIER pods quarantined (non-carriers are untouched
  by the group, so clean placements are preserved exactly).

`guarded_schedule_batch` composes all three with `core.schedule_batch`
in ONE jitted program — no new host sync; the service reads back a
single packed [word, bad_nodes, bad_pods] health vector (u32[3]) and
only touches the masks on the cold path, when the word is non-zero.
On healthy inputs every scrub is a `jnp.where` over an all-false mask,
so the scheduled columns are bit-identical to the unguarded program
(tools/chaos_smoke.py pins placements either way).

Word layout (u32; bit set = defect class present anywhere):
  bit 0  NODE_METRIC_NONFINITE   NaN/Inf in a metric-derived column
  bit 1  NODE_BAD_ALLOCATABLE    negative/non-finite allocatable
  bit 2  NODE_BAD_REQUESTED      negative/non-finite requested
  bit 3  NODE_OVERCOMMIT         requested > allocatable + tol
  bit 4  NODE_NUMA_INVALID       numa_free < 0 / > cap / non-finite
  bit 8  POD_NONFINITE           NaN/Inf in requests/estimated
  bit 9  POD_NEGATIVE            negative requests/estimated
  bit 10 POD_ID_RANGE            gang/quota/selector/toleration id OOB
  bit 11 POD_DOMAIN_RANGE        domain-matrix entry outside [-1, D)
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    PodBatch,
    shape_contract,
)

# matches core.overcommit_ok's default tolerance: the guard must agree
# with the host-side invariant, or a snapshot the dryrun calls healthy
# would quarantine on device (and vice versa)
OVERCOMMIT_TOL = 1.0

HEALTH_OK = 0
NODE_METRIC_NONFINITE = 1 << 0
NODE_BAD_ALLOCATABLE = 1 << 1
NODE_BAD_REQUESTED = 1 << 2
NODE_OVERCOMMIT = 1 << 3
NODE_NUMA_INVALID = 1 << 4
POD_NONFINITE = 1 << 8
POD_NEGATIVE = 1 << 9
POD_ID_RANGE = 1 << 10
POD_DOMAIN_RANGE = 1 << 11

# bit -> stable defect name (metric labels, chaos assertions, logs)
DEFECT_NAMES = {
    NODE_METRIC_NONFINITE: "node_metric_nonfinite",
    NODE_BAD_ALLOCATABLE: "node_bad_allocatable",
    NODE_BAD_REQUESTED: "node_bad_requested",
    NODE_OVERCOMMIT: "node_overcommit",
    NODE_NUMA_INVALID: "node_numa_invalid",
    POD_NONFINITE: "pod_nonfinite",
    POD_NEGATIVE: "pod_negative",
    POD_ID_RANGE: "pod_id_range",
    POD_DOMAIN_RANGE: "pod_domain_range",
}


def decode_health_word(word: int) -> Tuple[str, ...]:
    """Host-side: the defect-class names set in a packed health word."""
    return tuple(name for bit, name in sorted(DEFECT_NAMES.items())
                 if int(word) & bit)


def _pack(flag_bits) -> jnp.ndarray:
    """OR scalar-bool flags into one u32 word."""
    word = jnp.uint32(0)
    for flag, bit in flag_bits:
        word = word | jnp.where(flag, jnp.uint32(bit), jnp.uint32(0))
    return word


def _row_nonfinite(col: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: any non-finite entry in the row's trailing axes."""
    bad = ~jnp.isfinite(col)
    return bad.reshape(bad.shape[0], -1).any(axis=1)


def _row_invalid(col: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: any negative or non-finite entry per row."""
    bad = ~jnp.isfinite(col) | (col < 0.0)
    return bad.reshape(bad.shape[0], -1).any(axis=1)


def _node_defects(snap: ClusterSnapshot):
    """-> (word u32[], node_bad bool[N]). Pure columnar reductions."""
    nodes = snap.nodes
    metric_cols = (nodes.usage, nodes.prod_usage, nodes.agg_usage,
                   nodes.assigned_estimated, nodes.assigned_correction,
                   nodes.prod_assigned_estimated,
                   nodes.prod_assigned_correction)
    bad_metric = _row_nonfinite(metric_cols[0])
    for col in metric_cols[1:]:
        bad_metric = bad_metric | _row_nonfinite(col)
    bad_alloc = _row_invalid(nodes.allocatable)
    bad_req = _row_invalid(nodes.requested)
    # NaN comparisons are False, so a non-finite row cannot mask an
    # overcommit bit it doesn't deserve — it trips its own class instead
    over = (nodes.requested > nodes.allocatable + OVERCOMMIT_TOL).any(axis=1)
    numa_bad_elem = (~jnp.isfinite(nodes.numa_free)
                     | (nodes.numa_free < 0.0)
                     | (nodes.numa_free > nodes.numa_cap + OVERCOMMIT_TOL))
    numa_bad_elem = numa_bad_elem & nodes.numa_valid[:, :, None]
    bad_numa = numa_bad_elem.reshape(numa_bad_elem.shape[0], -1).any(axis=1)
    node_bad = bad_metric | bad_alloc | bad_req | over | bad_numa
    word = _pack(((bad_metric.any(), NODE_METRIC_NONFINITE),
                  (bad_alloc.any(), NODE_BAD_ALLOCATABLE),
                  (bad_req.any(), NODE_BAD_REQUESTED),
                  (over.any(), NODE_OVERCOMMIT),
                  (bad_numa.any(), NODE_NUMA_INVALID)))
    return word, node_bad


def _id_oob(ids: jnp.ndarray, cap: int) -> jnp.ndarray:
    """-1 is the legitimate 'none' sentinel everywhere; anything below
    it or at/past the snapshot capacity reads a clipped (wrong) row."""
    return (ids < -1) | (ids >= cap)


_DOMAIN_FAMILIES = (
    # (gate switch attr, domain matrix, count surface, carrier matrix)
    ("has_spread", "spread_domain", "spread_count0", "spread_carrier"),
    ("has_anti", "anti_domain", "anti_count0", "anti_carrier"),
    ("has_aff", "aff_domain", "aff_count0", "aff_carrier"),
)


def _bad_domain_groups(pods: PodBatch):
    """Per family: bool[Gf] groups whose domain row holds an entry
    outside [-1, D). Families whose gate is compiled out yield None."""
    out = {}
    for switch, dom_f, cnt_f, _carrier in _DOMAIN_FAMILIES:
        if not getattr(pods, switch):
            out[dom_f] = None
            continue
        dom = getattr(pods, dom_f)
        d = getattr(pods, cnt_f).shape[1]
        out[dom_f] = ((dom < -1) | (dom >= d)).any(axis=1)
    return out


def _batch_defects(snap: ClusterSnapshot, pods: PodBatch):
    """-> (word u32[], pod_bad bool[P]). Defects are detected on EVERY
    row (a NaN in an invalid pad row still poisons batch-global
    matmuls); the caller drains only valid rows through the error
    chain."""
    bad_nonfinite = (_row_nonfinite(pods.requests)
                     | _row_nonfinite(pods.estimated)
                     | ~jnp.isfinite(pods.gpu_ratio))
    bad_neg = ((pods.requests < 0.0).any(axis=1)
               | (pods.estimated < 0.0).any(axis=1)
               | (pods.gpu_ratio < 0.0))
    n_gangs = snap.gangs.min_member.shape[0]
    n_quotas = snap.quotas.parent.shape[0]
    n_sel = pods.selector_match.shape[0]
    n_tol = pods.tol_forbid.shape[0]
    bad_id = (_id_oob(pods.gang_id, n_gangs)
              | _id_oob(pods.quota_id, n_quotas)
              | _id_oob(pods.selector_id, n_sel)
              | _id_oob(pods.toleration_id, n_tol))
    bad_groups = _bad_domain_groups(pods)
    bad_domain_pod = jnp.zeros(pods.requests.shape[:1], bool)
    any_bad_group = jnp.asarray(False)
    for _switch, dom_f, _cnt_f, carrier_f in _DOMAIN_FAMILIES:
        bg = bad_groups[dom_f]
        if bg is None:
            continue
        carrier = getattr(pods, carrier_f)
        bad_domain_pod = bad_domain_pod | (carrier & bg[None, :]).any(axis=1)
        any_bad_group = any_bad_group | bg.any()
    pod_bad = bad_nonfinite | bad_neg | bad_id | bad_domain_pod
    word = _pack(((bad_nonfinite.any(), POD_NONFINITE),
                  (bad_neg.any(), POD_NEGATIVE),
                  (bad_id.any(), POD_ID_RANGE),
                  (any_bad_group, POD_DOMAIN_RANGE)))
    return word, pod_bad


def _scrub_rows(col: jnp.ndarray, bad: jnp.ndarray) -> jnp.ndarray:
    """Replace bad rows with their sanitized (finite, non-negative)
    values; healthy rows pass through bit-identically."""
    clean = jnp.maximum(
        jnp.nan_to_num(col, nan=0.0, posinf=0.0, neginf=0.0), 0.0)
    return jnp.where(bad.reshape(bad.shape + (1,) * (col.ndim - 1)),
                     clean, col)


def _quarantine(snap: ClusterSnapshot, pods: PodBatch,
                node_bad: jnp.ndarray, pod_bad: jnp.ndarray):
    nodes = snap.nodes
    alloc = _scrub_rows(nodes.allocatable, node_bad)
    # clamp the capacity-consistency defects too (requested within
    # allocatable, numa_free within cap): the scrubbed snapshot is what
    # gets COMMITTED, so a defect the scrub leaves in place would
    # re-trip the guard word — and re-count the same node on every
    # metric — each cycle until a full re-publish
    requested = _scrub_rows(nodes.requested, node_bad)
    requested = jnp.where(node_bad[:, None],
                          jnp.minimum(requested, alloc), requested)
    numa_free = _scrub_rows(nodes.numa_free, node_bad)
    numa_free = jnp.where(node_bad[:, None, None],
                          jnp.minimum(numa_free, nodes.numa_cap),
                          numa_free)
    nodes = nodes.replace(
        schedulable=nodes.schedulable & ~node_bad,
        allocatable=alloc,
        requested=requested,
        usage=_scrub_rows(nodes.usage, node_bad),
        prod_usage=_scrub_rows(nodes.prod_usage, node_bad),
        agg_usage=_scrub_rows(nodes.agg_usage, node_bad),
        assigned_estimated=_scrub_rows(nodes.assigned_estimated, node_bad),
        assigned_correction=_scrub_rows(nodes.assigned_correction,
                                        node_bad),
        prod_assigned_estimated=_scrub_rows(nodes.prod_assigned_estimated,
                                            node_bad),
        prod_assigned_correction=_scrub_rows(
            nodes.prod_assigned_correction, node_bad),
        numa_free=numa_free,
    )
    updates = dict(
        valid=pods.valid & ~pod_bad,
        requests=_scrub_rows(pods.requests, pod_bad),
        estimated=_scrub_rows(pods.estimated, pod_bad),
        gpu_ratio=_scrub_rows(pods.gpu_ratio, pod_bad),
    )
    # a domain row with out-of-range entries is scrubbed to -1 (node
    # lacks the label); its carriers are already in pod_bad, so no
    # clean pod is ever gated by the scrubbed group
    bad_groups = _bad_domain_groups(pods)
    for _switch, dom_f, _cnt_f, _carrier_f in _DOMAIN_FAMILIES:
        bg = bad_groups[dom_f]
        if bg is None:
            continue
        dom = getattr(pods, dom_f)
        updates[dom_f] = jnp.where(bg[:, None], -1, dom)
    return snap.replace(nodes=nodes), pods.replace(**updates)


@shape_contract(snap="ClusterSnapshot",
                _returns=("u32[]", "bool[N~pad:false]"),
                _pad="pad node rows are zero-capacity and scan healthy; "
                     "the word ORs defect-class bits over ALL rows")
@jax.jit
def snapshot_health(snap: ClusterSnapshot):
    """Scan the node columns; -> (packed health word, quarantine mask)."""
    return _node_defects(snap)


@shape_contract(snap="ClusterSnapshot", pods="PodBatch",
                _returns=("u32[]", "bool[P~pad:false]"),
                _pad="defects are detected on every row including "
                     "invalid pads (they still poison batch-global "
                     "matmuls); callers drain only valid rows")
@jax.jit
def batch_health(snap: ClusterSnapshot, pods: PodBatch):
    """Scan the pod batch; -> (packed health word, quarantine mask)."""
    return _batch_defects(snap, pods)


@shape_contract(snap="ClusterSnapshot", pods="PodBatch",
                node_bad="bool[N~pad:false]", pod_bad="bool[P~pad:false]",
                _returns=("ClusterSnapshot", "PodBatch"),
                _pad="all-false masks are a bit-identical pass-through")
@jax.jit
def apply_quarantine(snap: ClusterSnapshot, pods: PodBatch,
                     node_bad: jnp.ndarray, pod_bad: jnp.ndarray):
    """Neutralize flagged rows: bad nodes unschedulable + scrubbed, bad
    pods invalid + scrubbed, bad domain groups scrubbed to -1."""
    return _quarantine(snap, pods, node_bad, pod_bad)


@shape_contract(
    snap="ClusterSnapshot", pods="PodBatch", cfg="LoadAwareConfig",
    _returns=("ScheduleResult", "u32[3]", "bool[N~pad:false]",
              "bool[P~pad:false]"),
    _static={"num_rounds": 2, "k_choices": 2, "quota_depth": 2},
    _pad="quarantined rows behave exactly like schedulable=False nodes "
         "/ valid=False pods; health is [word, bad_nodes, bad_pods] "
         "packed for a single cold-path readback")
@functools.partial(jax.jit, static_argnames=("num_rounds", "k_choices",
                                             "score_dims", "approx_topk",
                                             "tie_break", "enable_numa",
                                             "numa_strategy",
                                             "enable_devices",
                                             "device_strategy",
                                             "quota_depth",
                                             "fit_dims",
                                             "enable_amplification",
                                             "topo_prefix",
                                             "dom_classes",
                                             "numa_prefix",
                                             "gpu_prefix",
                                             "cascade"))
def guarded_schedule_batch(snap: ClusterSnapshot, pods: PodBatch,
                           cfg: loadaware.LoadAwareConfig,
                           num_rounds: int = 4, k_choices: int = 8,
                           score_dims: tuple = None,
                           approx_topk: bool = False,
                           tie_break: bool = False,
                           enable_numa: bool = True,
                           numa_strategy: str = "most",
                           enable_devices: bool = True,
                           device_strategy: str = "least",
                           quota_depth: int = MAX_QUOTA_DEPTH,
                           fit_dims: tuple = None,
                           enable_amplification: bool = False,
                           topo_prefix: int = None,
                           dom_classes: tuple = None,
                           numa_prefix: int = None,
                           gpu_prefix: int = None,
                           cascade: bool = False):
    """Health guards + quarantine + `core.schedule_batch`, fused as ONE
    device program (same static knobs, same placement semantics on
    healthy inputs). Returns `(result, health, node_bad, pod_bad)` with
    `health = [packed word, quarantined nodes, quarantined pods]` as a
    single u32[3] vector — the service's one guard readback; the masks
    stay on device until the word says there is something to read."""
    node_word, node_bad = _node_defects(snap)
    pod_word, pod_bad = _batch_defects(snap, pods)
    g_snap, g_pods = _quarantine(snap, pods, node_bad, pod_bad)
    result = core.schedule_batch(
        g_snap, g_pods, cfg, num_rounds=num_rounds, k_choices=k_choices,
        score_dims=score_dims, approx_topk=approx_topk,
        tie_break=tie_break, enable_numa=enable_numa,
        numa_strategy=numa_strategy, enable_devices=enable_devices,
        device_strategy=device_strategy, quota_depth=quota_depth,
        fit_dims=fit_dims, enable_amplification=enable_amplification,
        topo_prefix=topo_prefix, dom_classes=dom_classes,
        numa_prefix=numa_prefix, gpu_prefix=gpu_prefix, cascade=cascade)
    health = jnp.stack([node_word | pod_word,
                        node_bad.sum().astype(jnp.uint32),
                        pod_bad.sum().astype(jnp.uint32)])
    return result, health, node_bad, pod_bad
