"""Host-side CPU-set accumulator: exact per-core cpuset assignment at bind
time.

Behavior parity with plugins/nodenumaresource/cpu_accumulator.go:
- `take_cpus` (takeCPUs, cpu_accumulator.go:87-245): picks `num_needed`
  logical CPUs from a node's topology honoring
  - CPUBindPolicy FullPCPUs: prefer fully-free physical cores, whole-core
    granularity, NUMA node chosen per NUMAAllocateStrategy
    (cpu_accumulator.go:105-178: free cores in node, then socket, then
    cross-socket);
  - CPUBindPolicy SpreadByPCPUs: one CPU per physical core round-robin,
    cores ordered by ref count then strategy
    (cpu_accumulator.go:179-244, spreadCPUs :798);
  - NUMAAllocateStrategy MostAllocated packs the fullest NUMA node,
    LeastAllocated spreads to the freest (sortCores :345-370);
  - maxRefCount: a CPU may be shared by up to maxRefCount LSR pods
    (newCPUAccumulator :247-288);
  - CPUExclusivePolicy PCPULevel: avoid cores carrying another exclusive
    pod's CPUs (isCPUExclusivePCPULevel :318-324).
- `take_preferred_cpus` (takePreferredCPUs :29-85): reservation-reserved
  CPUs are taken first.

This runs per placed pod on its chosen node only (the reference calls it in
Reserve, not the Filter/Score hot loop), so it stays host-side Python; the
device kernels (numaaware.py) already did zone-level admission.

Deviations (documented): socket-level sorting uses the same strategy key as
node-level rather than the reference's two-level core sort; exclusive
policy NUMANodeLevel is approximated by PCPULevel semantics at node scope.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set


@dataclasses.dataclass(frozen=True)
class CPUInfo:
    """One logical CPU (CPUTopology cpu detail)."""

    cpu: int
    core: int
    node: int      # NUMA node id
    socket: int


@dataclasses.dataclass
class CPUTopology:
    """Node CPU topology mirrored from NodeResourceTopology
    (topology_options.go CPUTopology)."""

    cpus: List[CPUInfo]

    def __post_init__(self):
        self.by_cpu = {c.cpu: c for c in self.cpus}
        self.cores: Dict[int, List[CPUInfo]] = {}
        self.nodes: Dict[int, List[CPUInfo]] = {}
        for c in self.cpus:
            self.cores.setdefault(c.core, []).append(c)
            self.nodes.setdefault(c.node, []).append(c)

    @property
    def cpus_per_core(self) -> int:
        return max(len(v) for v in self.cores.values()) if self.cores else 1

    @property
    def cpus_per_node(self) -> int:
        return max(len(v) for v in self.nodes.values()) if self.nodes else 0

    @staticmethod
    def uniform(num_sockets: int, nodes_per_socket: int,
                cores_per_node: int, threads_per_core: int = 2
                ) -> "CPUTopology":
        """Build a regular topology (test fixture / synthetic clusters)."""
        cpus = []
        cpu_id = 0
        core_id = 0
        for s in range(num_sockets):
            for n in range(nodes_per_socket):
                node_id = s * nodes_per_socket + n
                for _ in range(cores_per_node):
                    for _ in range(threads_per_core):
                        cpus.append(CPUInfo(cpu=cpu_id, core=core_id,
                                            node=node_id, socket=s))
                        cpu_id += 1
                    core_id += 1
        return CPUTopology(cpus)


class CPUAllocationError(Exception):
    """not enough cpus available to satisfy request
    (cpu_accumulator.go:103)."""


def _ref(allocated: Dict[int, int], cpu: int) -> int:
    return allocated.get(cpu, 0)


def take_cpus(topology: CPUTopology,
              available: Set[int],
              allocated: Dict[int, int],
              num_needed: int,
              bind_policy: str = "FullPCPUs",
              exclusive_policy: str = "",
              numa_strategy: str = "most",
              max_ref_count: int = 1,
              exclusive_cores: Optional[Set[int]] = None) -> List[int]:
    """Pick `num_needed` CPUs; raises CPUAllocationError when impossible.

    `allocated` maps cpu -> current ref count; `exclusive_cores` are cores
    carrying another PCPU-exclusive pod's CPUs.
    """
    exclusive_cores = exclusive_cores or set()
    usable = sorted(c for c in available
                    if _ref(allocated, c) < max_ref_count)
    if len(usable) < num_needed:
        raise CPUAllocationError(
            f"need {num_needed} cpus, only {len(usable)} usable")
    if num_needed == 0:
        return []

    usable_set = set(usable)
    pcpu_exclusive = exclusive_policy == "PCPULevel"

    def node_key(node_id: int):
        """NUMA strategy sort key: free CPUs in the node (MostAllocated
        packs the node with the fewest free; ties by node id)."""
        free = sum(1 for c in topology.nodes.get(node_id, ())
                   if c.cpu in usable_set)
        return (free, node_id) if numa_strategy == "most" else (-free, node_id)

    taken: List[int] = []

    if bind_policy == "FullPCPUs" or topology.cpus_per_core == 1:
        # fully-free cores grouped by NUMA node, exclusive-filtered first
        # then not (filterExclusiveArgs, cpu_accumulator.go:109)
        for filter_exclusive in ((True, False) if pcpu_exclusive
                                 else (False,)):
            for node_id in sorted(topology.nodes, key=node_key):
                if len(taken) >= num_needed:
                    break
                for core_id in sorted(
                        {c.core for c in topology.nodes[node_id]}):
                    if len(taken) >= num_needed:
                        break
                    if filter_exclusive and core_id in exclusive_cores:
                        continue
                    members = topology.cores[core_id]
                    # whole cores only — a partial core here would leave a
                    # sibling shared with another pod, defeating FullPCPUs;
                    # a non-multiple remainder goes through the spread
                    # fallback instead (the reference rejects non-multiples
                    # at Filter)
                    if len(members) > num_needed - len(taken):
                        continue
                    if all(m.cpu in usable_set and m.cpu not in taken
                           for m in members):
                        taken.extend(m.cpu for m in members)
            if len(taken) >= num_needed:
                return taken
        # not enough full cores: fall through to spread for the remainder
        bind_policy = "SpreadByPCPUs"

    # SpreadByPCPUs: rounds of one CPU per core; cores ordered by
    # (ref count of least-referenced cpu, NUMA strategy, core id)
    remaining = [c for c in usable if c not in taken]
    if pcpu_exclusive:
        non_excl = [c for c in remaining
                    if topology.by_cpu[c].core not in exclusive_cores]
        if len(taken) + len(non_excl) >= num_needed:
            remaining = non_excl
    per_core: Dict[int, List[int]] = {}
    for c in remaining:
        per_core.setdefault(topology.by_cpu[c].core, []).append(c)
    for core_cpus in per_core.values():
        core_cpus.sort(key=lambda c: (_ref(allocated, c), c))

    def core_order(core_id: int):
        head = per_core[core_id][0]
        return (_ref(allocated, head),
                node_key(topology.by_cpu[head].node), core_id)

    while len(taken) < num_needed:
        progressed = False
        for core_id in sorted((c for c in per_core if per_core[c]),
                              key=core_order):
            if len(taken) >= num_needed:
                break
            taken.append(per_core[core_id].pop(0))
            progressed = True
        if not progressed:
            raise CPUAllocationError("exhausted usable cpus")
    return taken


def take_preferred_cpus(topology: CPUTopology,
                        available: Set[int],
                        preferred: Set[int],
                        allocated: Dict[int, int],
                        num_needed: int,
                        **kw) -> List[int]:
    """Reservation-reserved CPUs first, then the rest
    (takePreferredCPUs, cpu_accumulator.go:29-85)."""
    result: List[int] = []
    max_ref = kw.get("max_ref_count", 1)
    pref = available & preferred
    usable_pref = {c for c in pref if _ref(allocated, c) < max_ref}
    if usable_pref:
        want = min(num_needed, len(usable_pref))
        result = take_cpus(topology, usable_pref, allocated, want, **kw)
        num_needed -= len(result)
        available = available - pref
    if num_needed > 0:
        result += take_cpus(topology, available - set(result), allocated,
                            num_needed, **kw)
    return result
