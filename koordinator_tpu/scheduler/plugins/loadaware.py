"""LoadAwareScheduling as batched JAX kernels.

Behavior parity with plugins/loadaware/load_aware.go:
- Filter (load_aware.go:123-254): reject a node when its (aggregated)
  utilization percentage meets a per-resource threshold; prod pods are gated
  on prod-tier usage when ProdUsageThresholds is set; nodes without a fresh
  NodeMetric pass (missing koordlet is tolerated); DaemonSet pods pass.
- Score (load_aware.go:269-335): estimatedUsed = estimator(pod) + Σ
  estimates of recently-assigned pods + node usage (instant or percentile),
  scored with weighted least-requested (load_aware.go:378-397).

The whole plugin is two dense [P, N] kernels; the reference's per-node map
lookups become gathers on NodeState columns. Integer-division semantics of
the Go scorer (floor) are reproduced in float32.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import flax.struct
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.extension import NUM_RESOURCES, PriorityClass, ResourceKind
from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
from koordinator_tpu.snapshot.schema import (
    AGG_TYPES,
    NodeState,
    PodBatch,
    register_struct,
    shape_contract,
)


@flax.struct.dataclass
class LoadAwareConfig:
    """Device-side LoadAwareSchedulingArgs (scheduler config types.go:30-58).

    Threshold/weight vectors are indexed by ResourceKind; 0 disables a
    resource (matching the reference's "threshold == 0 -> skip").
    `filter_agg_idx` / `score_agg_idx` select a percentile row in
    NodeState.agg_usage; -1 means instant usage.
    """

    resource_weights: jnp.ndarray      # f32[R]
    usage_thresholds: jnp.ndarray      # f32[R] percent
    prod_usage_thresholds: jnp.ndarray # f32[R] percent (all-zero = disabled)
    agg_usage_thresholds: jnp.ndarray  # f32[R] percent (aggregated profile)
    filter_agg_idx: jnp.ndarray        # i32[] row into AGG_TYPES, -1 = instant
    score_agg_idx: jnp.ndarray         # i32[] row into AGG_TYPES, -1 = instant
    score_according_prod_usage: jnp.ndarray  # bool[]

    @staticmethod
    def make(resource_weights: Optional[Mapping[ResourceKind, float]] = None,
             usage_thresholds: Optional[Mapping[ResourceKind, float]] = None,
             prod_usage_thresholds: Optional[Mapping[ResourceKind, float]] = None,
             agg_usage_thresholds: Optional[Mapping[ResourceKind, float]] = None,
             filter_agg_type: str = "",
             score_agg_type: str = "",
             score_according_prod_usage: bool = False) -> "LoadAwareConfig":
        def vec(m, default):
            out = np.zeros((NUM_RESOURCES,), np.float32)
            for k, v in (default if m is None else m).items():
                out[int(k)] = v
            return out

        default_weights = {ResourceKind.CPU: 1.0, ResourceKind.MEMORY: 1.0}
        default_thresholds = {ResourceKind.CPU: 65.0, ResourceKind.MEMORY: 95.0}
        return LoadAwareConfig(
            resource_weights=jnp.asarray(vec(resource_weights, default_weights)),
            usage_thresholds=jnp.asarray(vec(usage_thresholds, default_thresholds)),
            prod_usage_thresholds=jnp.asarray(vec(prod_usage_thresholds, {})),
            agg_usage_thresholds=jnp.asarray(vec(agg_usage_thresholds, {})),
            filter_agg_idx=jnp.int32(AGG_TYPES.index(filter_agg_type)
                                     if filter_agg_type else -1),
            score_agg_idx=jnp.int32(AGG_TYPES.index(score_agg_type)
                                    if score_agg_type else -1),
            score_according_prod_usage=jnp.asarray(score_according_prod_usage),
        )


register_struct(LoadAwareConfig, {
    "resource_weights": "f32[R]",
    "usage_thresholds": "f32[R]",
    "prod_usage_thresholds": "f32[R]",
    "agg_usage_thresholds": "f32[R]",
    "filter_agg_idx": "i32[]",
    "score_agg_idx": "i32[]",
    "score_according_prod_usage": "bool[]",
})


def _usage_percent(used: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """math.Round(used/total*100), 0 where total == 0 (filterNodeUsage math).

    Go math.Round is half-away-from-zero; jnp.round would be half-to-even
    and flip decisions at exact .5 boundaries. Values are >= 0 here.
    """
    pct = jnp.where(total > 0,
                    jnp.floor(used / jnp.maximum(total, 1e-9) * 100.0 + 0.5),
                    0.0)
    return pct


@shape_contract(nodes="NodeState", pods="PodBatch", cfg="LoadAwareConfig",
                _returns="bool[P~pad:any,N~pad:one]",
                _pad="nodes without fresh metrics pass (metric_fresh "
                     "False == padded rows pass; schedulable gates them "
                     "downstream); DaemonSet pods pass everywhere")
def filter_mask(nodes: NodeState, pods: PodBatch,
                cfg: LoadAwareConfig) -> jnp.ndarray:
    """bool[P, N]: True = node passes the LoadAware filter for the pod.

    Mirrors Plugin.Filter (load_aware.go:123-254). Per-node custom
    usage-threshold annotations are folded into the snapshot upstream.
    """
    alloc = nodes.allocatable                     # [N, R]
    # Instant- or percentile-usage source for the standard gate. When the
    # aggregated profile is configured but a node has no percentile data,
    # getTargetAggregatedUsage returns nil and the node passes -> usage 0.
    agg_row = jnp.take(nodes.agg_usage, jnp.maximum(cfg.filter_agg_idx, 0),
                       axis=1)                    # [N, R]
    used = jnp.where(
        cfg.filter_agg_idx >= 0,
        jnp.where(nodes.has_agg[:, None], agg_row, 0.0),
        nodes.usage)
    thresholds = jnp.where(cfg.filter_agg_idx >= 0, cfg.agg_usage_thresholds,
                           cfg.usage_thresholds)  # [R]

    pct = _usage_percent(used, alloc)             # [N, R]
    over = (thresholds[None, :] > 0) & (alloc > 0) & (pct >= thresholds[None, :])
    node_ok = ~jnp.any(over, axis=-1)             # [N]

    # prod gate (filterProdUsage, load_aware.go:228-254)
    prod_pct = _usage_percent(nodes.prod_usage, alloc)
    prod_over = ((cfg.prod_usage_thresholds[None, :] > 0) & (alloc > 0)
                 & (prod_pct >= cfg.prod_usage_thresholds[None, :]))
    prod_node_ok = ~jnp.any(prod_over, axis=-1)   # [N]

    has_prod_gate = jnp.any(cfg.prod_usage_thresholds > 0)
    is_prod = pods.priority_class == int(PriorityClass.PROD)  # [P]
    use_prod_gate = has_prod_gate & is_prod        # [P]

    ok = jnp.where(use_prod_gate[:, None], prod_node_ok[None, :],
                   node_ok[None, :])               # [P, N]

    # nodes without fresh metrics pass; DaemonSet pods pass
    ok = ok | ~nodes.metric_fresh[None, :] | pods.daemonset[:, None]
    return ok


def _guarded_sub(source: jnp.ndarray, correction: jnp.ndarray) -> jnp.ndarray:
    """quantity.Sub(q) guarded by quantity.Cmp(q) >= 0 (load_aware.go:303-309)."""
    return source - jnp.where(source >= correction, correction, 0.0)


@shape_contract(nodes="NodeState", pods="PodBatch", cfg="LoadAwareConfig",
                _returns="f32[P~pad:any,N~pad:zero]",
                _pad="nodes without a fresh NodeMetric score 0")
def score_matrix(nodes: NodeState, pods: PodBatch,
                 cfg: LoadAwareConfig,
                 score_dims: Optional[tuple] = None) -> jnp.ndarray:
    """f32[P, N] in [0, 100]: weighted least-requested on estimated usage.

    Mirrors Plugin.Score (load_aware.go:269-335) + loadAwareSchedulingScorer
    (:378-397). Nodes without a fresh NodeMetric score 0.

    `score_dims`: static tuple of ResourceKind indices with nonzero weight
    (the reference iterates only resourceWeights keys, :382); restricting the
    [P, N, R] broadcast to those dims cuts HBM traffic ~R/len(score_dims)x.
    """
    if score_dims is not None:
        dims = np.array(score_dims, dtype=np.int32)
        nodes = nodes.replace(
            allocatable=nodes.allocatable[:, dims],
            usage=nodes.usage[:, dims],
            prod_usage=nodes.prod_usage[:, dims],
            agg_usage=nodes.agg_usage[:, :, dims],
            assigned_estimated=nodes.assigned_estimated[:, dims],
            assigned_correction=nodes.assigned_correction[:, dims],
            prod_assigned_estimated=nodes.prod_assigned_estimated[:, dims],
            prod_assigned_correction=nodes.prod_assigned_correction[:, dims])
        pods = pods.replace(estimated=pods.estimated[:, dims])
        cfg = cfg.replace(resource_weights=cfg.resource_weights[dims])
    alloc = nodes.allocatable                                    # [N, R]

    # --- non-prod path: node usage source (instant or percentile)
    agg_row = jnp.take(nodes.agg_usage, jnp.maximum(cfg.score_agg_idx, 0),
                       axis=1)                                   # [N, R]
    # scoreWithAggregation: missing percentile data contributes zero usage
    usage_src = jnp.where(
        cfg.score_agg_idx >= 0,
        jnp.where(nodes.has_agg[:, None], agg_row, 0.0),
        nodes.usage)                                             # [N, R]
    node_term = (nodes.assigned_estimated
                 + _guarded_sub(usage_src, nodes.assigned_correction))  # [N, R]

    # --- prod path: Σ prod pod usages excluding estimated ones
    prod_term = (nodes.prod_assigned_estimated
                 + jnp.maximum(nodes.prod_usage - nodes.prod_assigned_correction,
                               0.0))                             # [N, R]

    is_prod_scored = (cfg.score_according_prod_usage
                      & (pods.priority_class == int(PriorityClass.PROD)))  # [P]
    base = jnp.where(is_prod_scored[:, None, None], prod_term[None, :, :],
                     node_term[None, :, :])                      # [P, N, R]
    estimated_used = pods.estimated[:, None, :] + base           # [P, N, R]

    # leastRequestedScore with Go integer-division flooring (:389-397)
    cap = alloc[None, :, :]
    least = jnp.floor((cap - estimated_used) * MAX_NODE_SCORE
                      / jnp.maximum(cap, 1e-9))
    least = jnp.where((cap > 0) & (estimated_used <= cap), least, 0.0)
    weights = cfg.resource_weights
    weight_sum = jnp.maximum(jnp.sum(weights), 1e-9)
    score = jnp.floor(jnp.einsum("pnr,r->pn", least, weights) / weight_sum)

    return jnp.where(nodes.metric_fresh[None, :], score, 0.0)
