"""ElasticQuota overuse revocation + cross-pod preemption victim selection.

Behavior parity with plugins/elasticquota/{quota_overuse_revoke.go,
preempt.go} (SURVEY.md 2.1):

- OVERUSE REVOKE: a per-quota monitor trips when used > runtime
  CONTINUOUSLY for the trigger duration (the waterfilled runtime shrinks
  when other quotas' demand grows — quota_overuse_revoke.go:61-90). Victim
  choice (:92-148): walk assigned pods from least to most important,
  revoking until used <= runtime; then try to "assign back" from most to
  least important, keeping only the revocations that are actually needed
  (a large low-priority pod may cover several small ones).
- PREEMPTION (SelectVictimsOnNode :111-220): candidates are lower-priority
  pods of the SAME quota on the node; remove them all, confirm the
  preemptor then fits node capacity and quota runtime, and reprieve
  highest-priority-first every candidate whose return still leaves the
  preemptor schedulable.

Both run on host over typed pods — these are rare, per-pod slow paths in
the reference too (PostFilter / a background controller), so they stay off
the batched device kernels by design.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.scheduler.batching import EPS
from koordinator_tpu.scheduler.preemption import (
    preemptible,
    reprieve_victims,
)
from koordinator_tpu.snapshot.builder import resource_vec


def _fits(used: np.ndarray, limit: np.ndarray) -> bool:
    # the SAME tolerance as scheduler/preemption.fits and the device
    # kernels (batching.EPS) — the two preemption paths and the device
    # program must agree on boundary fits
    return bool((used <= limit + EPS).all())


# --- overuse revoke ---------------------------------------------------------


class QuotaOverUsedGroupMonitor:
    """One quota's overuse tracker (quota_overuse_revoke.go:45-90)."""

    def __init__(self, quota_name: str,
                 trigger_evict_duration_seconds: float = 300.0):
        self.quota_name = quota_name
        self.trigger = trigger_evict_duration_seconds
        self._last_under_used: Optional[float] = None

    def monitor(self, used: np.ndarray, runtime: np.ndarray,
                now: float) -> bool:
        """True when overuse persisted past the trigger duration."""
        if self._last_under_used is None:
            self._last_under_used = now
        if _fits(used, runtime):
            self._last_under_used = now
            return False
        if now - self._last_under_used > self.trigger:
            self._last_under_used = now
            return True
        return False


def select_revoke_victims(pods: Sequence[api.Pod], used: np.ndarray,
                          runtime: np.ndarray) -> List[api.Pod]:
    """getToRevokePodList (:92-148): revoke least-important-first until
    used <= runtime, then assign back most-important-first where possible.
    Non-preemptible pods are skipped."""
    order = sorted(pods, key=lambda p: (p.priority or 0))
    tried: List[api.Pod] = []
    u = used.astype(np.float64).copy()
    for pod in order:
        if _fits(u, runtime):
            break
        if pod.meta.annotations.get("scheduling.koordinator.sh/preemptible") \
                == "false":
            continue
        u -= resource_vec(pod.requests)
        tried.append(pod)
    if not _fits(u, runtime):
        return tried  # even revoking everything preemptible is not enough
    revoked: List[api.Pod] = []
    for pod in reversed(tried):
        req = resource_vec(pod.requests)
        u += req
        if not _fits(u, runtime):
            u -= req
            revoked.append(pod)
    return revoked


class QuotaOverUsedRevokeController:
    """Drives the per-quota monitors over the live quota snapshot
    (used/runtime arrays from the waterfill kernel) and emits the pods to
    evict (quota_overuse_revoke.go:149-273)."""

    def __init__(self, trigger_evict_duration_seconds: float = 300.0):
        self.trigger = trigger_evict_duration_seconds
        self.monitors: Dict[str, QuotaOverUsedGroupMonitor] = {}

    def revoke_pods(self, quota_names: Sequence[str], used: np.ndarray,
                    runtime: np.ndarray,
                    pods_by_quota: Dict[str, Sequence[api.Pod]],
                    now: float) -> List[api.Pod]:
        """used/runtime: [Q, R] rows aligned with quota_names."""
        for stale in set(self.monitors) - set(quota_names):
            del self.monitors[stale]
        out: List[api.Pod] = []
        for qi, name in enumerate(quota_names):
            mon = self.monitors.get(name)
            if mon is None:
                mon = self.monitors[name] = QuotaOverUsedGroupMonitor(
                    name, self.trigger)
            if mon.monitor(np.asarray(used[qi]), np.asarray(runtime[qi]),
                           now):
                out.extend(select_revoke_victims(
                    pods_by_quota.get(name, ()), np.asarray(used[qi]),
                    np.asarray(runtime[qi])))
        return out


# --- preemption -------------------------------------------------------------


@dataclasses.dataclass
class PreemptionResult:
    victims: List[api.Pod]
    message: str = ""


def select_victims_on_node(preemptor: api.Pod,
                           node_allocatable: np.ndarray,
                           pods_on_node: Sequence[api.Pod],
                           quota_used: np.ndarray,
                           quota_runtime: np.ndarray,
                           cpu_amplification: float = 1.0,
                           fine_fit: Optional[Callable] = None
                           ) -> Optional[PreemptionResult]:
    """SelectVictimsOnNode (preempt.go:111-220), quota-constrained: only
    lower-priority pods of the preemptor's OWN quota are candidates
    (canPreempt), and the preemptor must fit both the node and its quota
    runtime after the removals. Returns None when preemption on this node
    cannot help. The NODE fit charges amplified CPU for bind pods
    (matching the device gate); quota accounting stays RAW — quota trees
    meter requests, not node capacity. `fine_fit(survivors)` re-runs
    the fine-grained gates per reprieve step (preemption.
    fine_grained_admits — same contract as default preemption)."""
    from koordinator_tpu.scheduler.preemption import charged_request

    prio = preemptor.priority or 0

    def is_candidate(p: api.Pod) -> bool:
        return ((p.priority or 0) < prio
                and p.quota_name == preemptor.quota_name
                and preemptible(p))

    def raw(p: api.Pod) -> np.ndarray:
        return resource_vec(p.requests).astype(np.float64)

    def charged(p: api.Pod) -> np.ndarray:
        return charged_request(p, cpu_amplification)

    candidates = [p for p in pods_on_node if is_candidate(p)]
    if not candidates:
        return None

    others = [p for p in pods_on_node if not is_candidate(p)]
    req_node = charged(preemptor)
    req_raw = raw(preemptor)
    base_used = sum((charged(p) for p in others),
                    np.zeros_like(req_node))
    # quota used excluding every candidate (they are all removed first)
    cand_req = sum((raw(p) for p in candidates),
                   np.zeros_like(req_raw))
    q_used = quota_used.astype(np.float64) - cand_req

    # the same remove-all-then-reprieve minimal-set core the default
    # preemption uses, with the quota runtime as the extra fit surface
    def extra_fit(returned: np.ndarray, reprieved) -> bool:
        raw_returned = sum((raw(p) for p in reprieved),
                           np.zeros_like(req_raw))
        if not (_fits(base_used + returned + req_node, node_allocatable)
                and _fits(q_used + raw_returned + req_raw,
                          quota_runtime)):
            return False
        return fine_fit is None or fine_fit(others + list(reprieved))

    victims = reprieve_victims(req_node, candidates, extra_fit,
                               req_fn=charged)
    if victims is None:
        return None
    return PreemptionResult(victims=victims)
