"""DeviceShare plugin: batched GPU/RDMA/FPGA instance fit, scoring, and
instance-selection kernels.

Behavior parity with plugins/deviceshare/ (SURVEY.md 2.1):
- GPU requests arrive as gpu-core / gpu-memory / gpu-memory-ratio
  (apis/extension/device_share.go:44-46). Per node, an explicit gpu-memory
  request is converted to a ratio against that node's per-GPU memory and
  vice versa (devicehandler_gpu.go:68-90 fillGPUTotalMem); a ratio > 100
  divisible by 100 means `ratio/100` whole GPUs with the request split
  evenly per instance (devicehandler_gpu.go:54-64).
- Allocation packs `count` instances each satisfying the per-instance
  request on all three dims (device_allocator.go allocateDevices); instance
  preference follows the least/most-allocated scorer (device_resources.go
  scoreDevices).
- RDMA/FPGA follow the default device handler: one instance (VF pool)
  serves the whole request (devicehandler_default.go).
- Node score is the least/most-allocated fraction over the node's GPU pool
  (scoring.go resourceAllocationScorer), 0 for pods without device requests.

TPU design: device instances are fixed-capacity columns ([N, I, 3] GPU,
[N, A, J] aux); the per-node allocator loop becomes an argmax over the
instance axis, and concurrent instance commits reuse the segment prefix gate
with flattened (node, instance) segment ids — the same machinery as NUMA
zones. Multi-GPU pods consume whole instances; identity among interchangeable
fully-free instances is the lowest-index prefix, with at most one multi-GPU
pod admitted per node per inner commit step (losers fall through to the next
step/round), which keeps instance identity unambiguous without a sort.

Documented deviations (tracked for later rounds): PCIe joint-allocate is a
bind-time minor-ordering preference on host (allocation counts are
identical); device capacity covered by Reservations is not restored
(device-requesting pods schedule on real nodes only).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from koordinator_tpu.api.extension import ResourceKind
from koordinator_tpu.scheduler.batching import EPS, MAX_NODE_SCORE
from koordinator_tpu.snapshot.schema import (
    AUX_FPGA,
    AUX_RDMA,
    DEV_CORE,
    DEV_MEM,
    DEV_RATIO,
    DeviceState,
    PodBatch,
    shape_contract,
)

GPU_CORE = int(ResourceKind.GPU_CORE)
GPU_MEMORY = int(ResourceKind.GPU_MEMORY)
# aux pool index -> ResourceKind column carrying the request
AUX_KINDS = (int(ResourceKind.RDMA), int(ResourceKind.FPGA))


def has_gpu_request(pods: PodBatch) -> jnp.ndarray:
    """bool[P]: pod requests any GPU resource (requests may also be a
    broadcast [P, N, R] view, hence the ellipsis indexing)."""
    return ((pods.requests[..., GPU_CORE] > 0)
            | (pods.requests[..., GPU_MEMORY] > 0)
            | (pods.gpu_ratio > 0))


def has_device_request(pods: PodBatch) -> jnp.ndarray:
    """bool[P]: pod requests any device resource (GPU or aux pools)."""
    out = has_gpu_request(pods)
    for kind in AUX_KINDS:
        out |= pods.requests[..., kind] > 0
    return out


def _per_instance(total_mem, pods: PodBatch):
    """Per-instance GPU request against nodes whose per-GPU memory is
    `total_mem` (broadcastable against [P]).

    Returns (count, per_inst[..., 3]) with the reference's integer floor
    division (devicehandler_gpu.go:54-64, memoryBytesToRatio truncation).
    Pods without GPU requests get count=0 and a zero per_inst row.
    """
    core = pods.requests[..., GPU_CORE]
    mem = pods.requests[..., GPU_MEMORY]
    mem_specified = mem > 0
    safe_total = jnp.maximum(total_mem, 1.0)
    ratio_eff = jnp.where(mem_specified,
                          jnp.floor(mem / safe_total * 100.0),
                          pods.gpu_ratio)
    mem_eff = jnp.where(mem_specified, mem,
                        jnp.floor(pods.gpu_ratio * total_mem / 100.0))
    multi = (ratio_eff > 100.0) & (jnp.mod(ratio_eff, 100.0) == 0.0)
    count = jnp.where(multi, ratio_eff / 100.0, 1.0)
    per_inst = jnp.stack([jnp.floor(core / count),
                          jnp.floor(mem_eff / count),
                          jnp.floor(ratio_eff / count)], axis=-1)
    gpu = has_gpu_request(pods)
    shape = jnp.broadcast_shapes(count.shape, gpu.shape)
    gpu = jnp.broadcast_to(gpu, shape)
    count = jnp.where(gpu, count, 0.0).astype(jnp.int32)
    per_inst = per_inst * gpu[..., None]
    return count, per_inst


@shape_contract(devices="DeviceState", pods="PodBatch",
                node_idx="i32[P~pad:-1]",
                _returns=("i32[P~pad:zero]", "f32[P~pad:zero,DEV]"),
                _pad="out-of-range node_idx (= no node) is clipped; "
                     "pods without GPU requests get count 0 and zero rows")
def per_instance_at(devices: DeviceState, pods: PodBatch,
                    node_idx: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(count i32[P], per_inst f32[P, 3]) at each pod's chosen node
    (node_idx may be out of range = "no node"; clipped)."""
    n = devices.gpu_total.shape[0]
    nc = jnp.clip(node_idx, 0, n - 1)
    return _per_instance(devices.gpu_total[nc, DEV_MEM], pods)


@shape_contract(devices="DeviceState", pods="PodBatch",
                _returns="bool[P~pad:one,N~pad:any]",
                _pad="non-device pods pass everywhere; invalid "
                     "instances (gpu_valid False) never count")
def prefilter(devices: DeviceState, pods: PodBatch) -> jnp.ndarray:
    """bool[P, N]: batch-start upper bound — the node has >= count instances
    each fitting the per-instance request, and every requested aux pool has
    a fitting instance. Free only shrinks during commit, so this is sound
    (the exact gate runs per inner commit step on the chosen node).
    Non-device pods pass everywhere."""
    total_mem = devices.gpu_total[None, :, DEV_MEM]          # [1, N]
    count, per_inst = _per_instance(
        total_mem, pods.replace(
            requests=pods.requests[:, None, :],
            gpu_ratio=pods.gpu_ratio[:, None]))              # [P, N], [P,N,3]
    fits = jnp.all(devices.gpu_free[None] + EPS >= per_inst[:, :, None, :],
                   axis=-1)
    fits &= devices.gpu_valid[None]                          # [P, N, I]
    n_fit = jnp.sum(fits, axis=-1)                           # [P, N]
    ok = ~has_gpu_request(pods)[:, None] | (n_fit >= count)
    for t, kind in enumerate(AUX_KINDS):
        req = pods.requests[:, kind]
        aux_ok = jnp.any(
            (devices.aux_free[None, :, t, :] + EPS >= req[:, None, None])
            & devices.aux_valid[None, :, t, :], axis=-1)     # [P, N]
        ok &= (req <= 0)[:, None] | aux_ok
    return ok


@shape_contract(devices="DeviceState", pods="PodBatch",
                _returns="f32[P~pad:zero,N~pad:any]",
                _pad="0 for pods without GPU requests")
def score_matrix(devices: DeviceState, pods: PodBatch,
                 strategy: str = "least") -> jnp.ndarray:
    """f32[P, N] in [0, 100]: least/most-allocated score of the node's GPU
    pool after the hypothetical allocation, over the dims the pod requests
    (scoring.go resourceAllocationScorer); 0 for pods without GPU requests.

    Default strategy is LeastAllocated (DeviceShareArgs defaulting,
    scheduler/apis/config/v1beta2/defaults.go).
    """
    total_mem = devices.gpu_total[None, :, DEV_MEM]
    count, per_inst = _per_instance(
        total_mem, pods.replace(
            requests=pods.requests[:, None, :],
            gpu_ratio=pods.gpu_ratio[:, None]))              # [P, N], [P,N,3]
    valid_n = jnp.sum(devices.gpu_valid, axis=-1)            # [N]
    pool_total = devices.gpu_total * valid_n[:, None]        # [N, 3]
    pool_free = jnp.sum(
        devices.gpu_free * devices.gpu_valid[..., None], axis=1)  # [N, 3]
    alloc = per_inst * count[..., None]                      # [P, N, 3]
    used_after = (pool_total - pool_free)[None] + alloc
    frac = used_after / jnp.maximum(pool_total[None], 1e-9)
    requested_dim = per_inst > 0                             # [P, N, 3]
    w = requested_dim.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w, axis=-1), 1.0)
    if strategy == "most":
        s = jnp.sum(frac * w, axis=-1) / wsum
    else:
        s = jnp.sum((1.0 - frac) * w, axis=-1) / wsum
    score = jnp.clip(s, 0.0, 1.0) * MAX_NODE_SCORE
    return jnp.where(has_gpu_request(pods)[:, None], score, 0.0)


def gpu_zone_counts(gpu_free: jnp.ndarray, devices: DeviceState,
                    node_idx: jnp.ndarray, per_inst: jnp.ndarray,
                    n_zones: int) -> jnp.ndarray:
    """i32[P, Z]: fitting instances per zone of the chosen node — the raw
    input of the deviceshare NUMATopologyHintProvider (topology_hint.go
    GetPodTopologyHints), consumed by topologymanager.count_hints."""
    n = gpu_free.shape[0]
    nc = jnp.clip(node_idx, 0, n - 1)
    fits = jnp.all(gpu_free[nc] + EPS >= per_inst[:, None, :], axis=-1)
    fits &= devices.gpu_valid[nc]                            # [P, I]
    zid = devices.gpu_numa[nc]                               # [P, I]
    onehot = zid[:, :, None] == jnp.arange(n_zones,
                                           dtype=zid.dtype)[None, None, :]
    return jnp.sum((fits[:, :, None] & onehot).astype(jnp.int32), axis=1)


def _zone_allowed(devices: DeviceState, nc: jnp.ndarray,
                  zone_mask: jnp.ndarray,
                  engaged: jnp.ndarray) -> jnp.ndarray:
    """bool[P, I]: instance is inside the pod's merged NUMA affinity.
    Topology-engaged pods may only take instances whose zone bit is set
    (unknown-zone instances excluded); unengaged pods take anywhere."""
    zid = devices.gpu_numa[nc]                               # [P, I]
    in_mask = jnp.take_along_axis(
        zone_mask, jnp.clip(zid, 0, zone_mask.shape[1] - 1), axis=1)
    return ~engaged[:, None] | (in_mask & (zid >= 0))


def choose_gpu_instance(gpu_free: jnp.ndarray, devices: DeviceState,
                        node_idx: jnp.ndarray, per_inst: jnp.ndarray,
                        shared: jnp.ndarray, zone_mask: jnp.ndarray,
                        engaged: jnp.ndarray,
                        strategy: str = "least"
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pick each shared-GPU pod's instance on its chosen node from live free
    state (the scoreDevices instance preference).

    Topology-engaged pods only take instances inside their merged NUMA
    affinity `zone_mask` bool[P, Z] (the hint providers' merge,
    topology_hint.go). Returns (inst i32[P], ok bool[P]); ok is True for
    pods the shared gate doesn't apply to. Exactness among contending pods
    comes from the caller's segment prefix gate over (node, instance) ids.
    """
    n = gpu_free.shape[0]
    nc = jnp.clip(node_idx, 0, n - 1)
    free = gpu_free[nc]                                      # [P, I, 3]
    fits = jnp.all(free + EPS >= per_inst[:, None, :], axis=-1)
    fits &= devices.gpu_valid[nc]                            # [P, I]
    fits &= _zone_allowed(devices, nc, zone_mask, engaged)
    # instance preference keyed on free core: least-allocated spreads
    # (freest instance), most-allocated packs (fullest fitting instance)
    key = free[..., DEV_CORE]
    if strategy == "most":
        key = jnp.where(fits, key, jnp.inf)
        inst = jnp.argmin(key, axis=-1).astype(jnp.int32)
    else:
        key = jnp.where(fits, key, -jnp.inf)
        inst = jnp.argmax(key, axis=-1).astype(jnp.int32)
    ok = jnp.any(fits, axis=-1) | ~shared
    return inst, ok


def full_fit_instances(gpu_free: jnp.ndarray, devices: DeviceState,
                       node_idx: jnp.ndarray, per_inst: jnp.ndarray,
                       count: jnp.ndarray, zone_mask: jnp.ndarray,
                       engaged: jnp.ndarray,
                       exclude: jnp.ndarray = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """For multi-GPU pods: (take bool[P, I], enough bool[P]) — the lowest-
    index `count` fitting instances on the chosen node, and whether there
    are at least `count` of them.

    Topology-engaged pods only take instances inside their merged NUMA
    affinity (same alignment rule as choose_gpu_instance); `exclude`
    bool[P, I] marks instances unavailable to this pod (e.g. tentatively
    taken by the same commit step's shared pods).
    """
    n = gpu_free.shape[0]
    nc = jnp.clip(node_idx, 0, n - 1)
    fits = jnp.all(gpu_free[nc] + EPS >= per_inst[:, None, :], axis=-1)
    fits &= devices.gpu_valid[nc]                            # [P, I]
    if exclude is not None:
        fits &= ~exclude
    fits &= _zone_allowed(devices, nc, zone_mask, engaged)
    enough = jnp.sum(fits, axis=-1) >= count
    cum = jnp.cumsum(fits.astype(jnp.int32), axis=-1)
    take = fits & (cum <= count[:, None])
    return take, enough


def choose_aux_instance(aux_free: jnp.ndarray, devices: DeviceState,
                        node_idx: jnp.ndarray, pool: int,
                        req: jnp.ndarray,
                        strategy: str = "least"
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pick an aux (RDMA/FPGA) instance with free >= req on the chosen
    node. Returns (inst i32[P], ok bool[P]); ok is True when req == 0."""
    n = aux_free.shape[0]
    nc = jnp.clip(node_idx, 0, n - 1)
    free = aux_free[nc, pool]                                # [P, J]
    fits = (free + EPS >= req[:, None]) & devices.aux_valid[nc, pool]
    key = jnp.where(fits, free, jnp.inf if strategy == "most" else -jnp.inf)
    inst = (jnp.argmin(key, axis=-1) if strategy == "most"
            else jnp.argmax(key, axis=-1)).astype(jnp.int32)
    ok = jnp.any(fits, axis=-1) | (req <= 0)
    return inst, ok
