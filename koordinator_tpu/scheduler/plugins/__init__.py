"""Scheduler plugins as pure batched kernels.

Each module mirrors one reference plugin (SURVEY.md 2.1) as functions over
(NodeState/ClusterSnapshot, PodBatch) returning [P, N] masks or scores.
"""
