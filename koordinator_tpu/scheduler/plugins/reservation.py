"""Reservation plugin: batched restore/consume semantics.

Behavior parity with plugins/reservation/ (SURVEY.md 2.1):
- A Reservation is scheduled ahead of time as a "reserve pod", so its full
  allocatable is already counted in node `requested`
  (transformer.go restoreUnmatchedReservations comment: reservations and
  consuming pods would otherwise be cumulative; the net accounting keeps
  exactly the reservation's allocatable charged).
- When a pending pod matches a reservation's owners, the reserved capacity
  is effectively returned to the pod's view of the node
  (transformer.go:240 restoreMatchedReservation), the nominator picks the
  reservation to consume, and Reserve allocates from it — so a consuming
  pod does NOT increase node `requested` for the covered portion
  (plugin.go:521-613).
- AllocateOnce reservations admit a single consumer and are then exhausted
  (plugin.go:509-510).

Batched TPU design: reservations are rare (V small), so instead of carrying
a [P, N, R] restore tensor through the hot feasibility kernel, a pre-pass
scans the V reservation slots: for each slot, all matching pods are admitted
in priority order against the slot's free capacity with an exact prefix-sum
gate (the sequential-assume equivalent), quota levels included. Pods the
pre-pass places skip the normal rounds; pods whose requests exceed the
remaining reserved capacity fall through and schedule as normal pods
(documented deviation: the reference lets a pod straddle reservation +
node free capacity; the pre-pass is all-or-nothing per pod, conservative
because reserved capacity stays charged to the node either way).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from koordinator_tpu.scheduler.batching import EPS, segment_prefix_ok
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    PodBatch,
    ReservationState,
)

MAX_NODE_SCORE = 100.0


def reservation_prepass(
    snap: ClusterSnapshot, pods: PodBatch,
    static_ok: jnp.ndarray, earlier: jnp.ndarray, pod_anc: jnp.ndarray,
    gang_ok: jnp.ndarray,
) -> Tuple[jnp.ndarray, ReservationState, jnp.ndarray]:
    """Consume matching reservations in priority order.

    Args:
      static_ok: bool[P, N] round-invariant node gates (selector, LoadAware,
        schedulable) — reservation consumers still pass Filter on the
        reservation's node (plugin.go Filter path).
      earlier: bool[P, P] rank[p'] < rank[p].
      pod_anc: i32[P, D] quota ancestor chain per pod (-1 = none).
      gang_ok: bool[P] gang quorum gate.

    Returns (placed, res_slot, quota_used'): placed is i32[P] with the
    reservation's node for admitted pods and -1 otherwise; res_slot is
    i32[P] with the consumed reservation slot (-1 = none) so the caller can
    rebuild reservation free after gang rollback; node `requested` is
    intentionally NOT modified (covered capacity was already charged).
    """
    resv = snap.reservations
    quotas = snap.quotas
    n_quotas = quotas.min.shape[0]
    p = pods.num_pods

    def body(carry, v):
        free_all, quota_used, placed, res_slot = carry
        node_v = resv.node[v]
        free_v = free_all[v]                                   # [R]

        eligible = (
            resv.valid[v] & (node_v >= 0)
            & (pods.reservation_owner >= 0)
            & (pods.reservation_owner == resv.owner_group[v])
            & pods.valid & gang_ok & (placed < 0))
        # Filter still applies on the reservation's node.
        node_c = jnp.maximum(node_v, 0)
        eligible &= static_ok[:, node_c]

        # --- AllocateOnce path: the winner is the first pod in priority
        # order that passes BOTH fit and quota (sequentially each pod tries
        # in turn; a quota-rejected candidate does not block later owners).
        # Only one pod consumes, so fit and quota are individual checks.
        quota_alone = jnp.ones((p,), bool)
        for d in range(MAX_QUOTA_DEPTH):
            anc = pod_anc[:, d]
            a = jnp.maximum(anc, 0)
            level_ok = jnp.all(quota_used[a] + pods.requests
                               <= quotas.runtime[a] + EPS, axis=-1)
            quota_alone &= (anc < 0) | level_ok
        once_cand = (eligible & quota_alone
                     & jnp.all(pods.requests <= free_v[None, :] + EPS,
                               axis=-1))
        once_accept = once_cand & ~jnp.any(earlier & once_cand[None, :],
                                           axis=-1)

        # --- Shared path: all-or-nothing fit within remaining reserved
        # capacity, exact in priority order: own request + Σ earlier
        # eligible same-slot pods, then quota prefix per tree level
        # (consuming a reservation still charges the pod's quota,
        # elasticquota plugin.go AddPod).
        eff_req = jnp.where(eligible[:, None], pods.requests, 0.0)
        cum_excl = (earlier & eligible[None, :]).astype(
            eff_req.dtype) @ eff_req                            # [P, R]
        shared_accept = eligible & jnp.all(
            cum_excl + pods.requests <= free_v[None, :] + EPS, axis=-1)
        for d in range(MAX_QUOTA_DEPTH):
            anc = jnp.where(shared_accept, pod_anc[:, d], -1)
            anc_eff = jnp.where(anc >= 0, anc, n_quotas)
            acc_req = jnp.where(shared_accept[:, None], pods.requests, 0.0)
            shared_accept &= segment_prefix_ok(
                anc_eff, earlier, acc_req, quota_used, quotas.runtime,
                n_quotas)

        accept = jnp.where(resv.allocate_once[v], once_accept, shared_accept)

        acc_req = pods.requests * accept[:, None]
        consumed = jnp.sum(acc_req, axis=0)                     # [R]
        any_acc = jnp.any(accept)
        new_free = jnp.where(
            resv.allocate_once[v] & any_acc,
            jnp.zeros_like(free_v),
            jnp.maximum(free_v - consumed, 0.0))
        free_all = free_all.at[v].set(new_free)
        for d in range(MAX_QUOTA_DEPTH):
            anc = jnp.where(accept, pod_anc[:, d], -1)
            quota_used = quota_used.at[
                jnp.where(anc >= 0, anc, n_quotas)].add(acc_req, mode="drop")
        placed = jnp.where(accept, node_v, placed)
        res_slot = jnp.where(accept, v, res_slot)
        return (free_all, quota_used, placed, res_slot), None

    n_res = resv.valid.shape[0]
    init = (resv.free, quotas.used, jnp.full((p,), -1, jnp.int32),
            jnp.full((p,), -1, jnp.int32))
    (_, quota_used, placed, res_slot), _ = jax.lax.scan(
        body, init, jnp.arange(n_res))
    return placed, res_slot, quota_used


def rebuild_reservations(resv: ReservationState, pods: PodBatch,
                         res_slot: jnp.ndarray,
                         ok: jnp.ndarray) -> ReservationState:
    """Final reservation state from the surviving assignment (pods the gang
    Permit barrier revoked give their reserved capacity back)."""
    n_res = resv.valid.shape[0]
    consuming = ok & (res_slot >= 0)
    tgt = jnp.where(consuming, res_slot, n_res)
    consumed = jnp.zeros_like(resv.free).at[tgt].add(
        pods.requests * consuming[:, None], mode="drop")
    took_once = jnp.zeros((n_res,), bool).at[tgt].max(
        consuming, mode="drop")
    new_free = jnp.where((resv.allocate_once & took_once)[:, None],
                         0.0, jnp.maximum(resv.free - consumed, 0.0))
    return resv.replace(free=new_free,
                        valid=resv.valid & ~(resv.allocate_once & took_once))
