"""Reservation plugin: batched restore/consume semantics.

Behavior parity with plugins/reservation/ (SURVEY.md 2.1):
- A Reservation is scheduled ahead of time as a "reserve pod", so its full
  allocatable is already counted in node `requested`
  (transformer.go restoreUnmatchedReservations comment: reservations and
  consuming pods would otherwise be cumulative; the net accounting keeps
  exactly the reservation's allocatable charged).
- When a pending pod matches a reservation's owners, the reserved capacity
  is effectively returned to the pod's view of the node
  (transformer.go:240 restoreMatchedReservation), the nominator picks the
  reservation to consume, and Reserve allocates from it — so a consuming
  pod does NOT increase node `requested` for the covered portion
  (plugin.go:521-613).
- AllocateOnce reservations admit a single consumer and are then exhausted
  (plugin.go:509-510).

Batched TPU design: reservations are rare (V small), so each reservation
slot becomes a VIRTUAL NODE column appended to the score/feasibility
matrices inside the normal commit rounds. The slot column's capacity is the
reservation's remaining free; only owner-matched pods see it (the restore +
nominate semantics); its score is MaxNodeScore so owners prefer it (the
nominator's reservation preference). Because slots ride the same
priority-ordered prefix gates as real nodes and quota levels, consumer
admission interleaves EXACTLY with normal pods — no separate pre-pass, no
priority inversion against non-consumers. AllocateOnce is a per-slot
single-winner gate inside the inner commit.

Documented deviation: the reference lets one pod straddle reservation +
node free capacity; here a pod either fits entirely within the reservation
free or schedules as a normal pod (conservative — the reserved capacity
stays charged to the node either way).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch, ReservationState


def slot_columns(snap: ClusterSnapshot, pods: PodBatch,
                 static_base: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Virtual-node columns for the V reservation slots.

    Returns (slot_ok [P, V], slot_alloc [V, R], slot_node i32[V]):
    - slot_ok: pod may consume slot v — owner match (transformer.go
      matched-owner restore) AND the slot's underlying node passes the
      pod's round-invariant gates BEFORE the device/NUMA prefilters
      (those reason about the node's open pools; a consumer draws from
      the hold). CPU-bind pods need a slot with a reserved zone; GPU pods
      a slot with reserved instances (their exact fit runs in the
      extended-row instance/zone gates). Aux (rdma/fpga) reservations are
      not modeled — a documented deviation.
    - slot_alloc: the slot's capacity = remaining reserved free.
    - slot_node: underlying real node per slot (-1 invalid).
    """
    from koordinator_tpu.scheduler.plugins import deviceshare

    resv = snap.reservations
    node_c = jnp.maximum(resv.node, 0)
    base_ok = (resv.valid & (resv.node >= 0))[None, :]           # [1, V]
    owner_ok = ((pods.reservation_owner[:, None] >= 0)
                & (pods.reservation_owner[:, None]
                   == resv.owner_group[None, :]))                # [P, V]
    has_zone = jnp.any(resv.numa_valid, axis=-1)                 # [V]
    has_gpu = jnp.any(resv.gpu_valid, axis=-1)                   # [V]
    has_aux = jnp.zeros((pods.num_pods,), bool)
    for kind in deviceshare.AUX_KINDS:
        has_aux |= pods.requests[:, kind] > 0
    slot_ok = (base_ok & owner_ok & static_base[:, node_c]
               & (~pods.numa_single[:, None] | has_zone[None, :])
               & (~deviceshare.has_gpu_request(pods)[:, None]
                  | has_gpu[None, :])
               & ~has_aux[:, None])
    return slot_ok, resv.free, resv.node


def rebuild_reservations(resv: ReservationState, pods: PodBatch,
                         res_slot: jnp.ndarray, ok: jnp.ndarray,
                         numa_take: jnp.ndarray = None,
                         gpu_take: jnp.ndarray = None,
                         gpu_per_inst: jnp.ndarray = None
                         ) -> ReservationState:
    """Final reservation state from the surviving assignment (pods the gang
    Permit barrier revoked give their reserved capacity back). Consumers'
    zone/instance takes are drawn down from the slot's fine-grained holds
    so the next cycle sees the remaining reserved minors/zone capacity."""
    n_res = resv.valid.shape[0]
    consuming = ok & (res_slot >= 0)
    tgt = jnp.where(consuming, res_slot, n_res)
    consumed = jnp.zeros_like(resv.free).at[tgt].add(
        pods.requests * consuming[:, None], mode="drop")
    took_once = jnp.zeros((n_res,), bool).at[tgt].max(
        consuming, mode="drop")
    # exhausted AllocateOnce slots keep their remainders (valid=False
    # already gates admission) so a later forget/un-assume can restore the
    # slot exactly (snapshot/delta.py forget_pods)
    exhausted = resv.allocate_once & took_once
    new_free = jnp.maximum(resv.free - consumed, 0.0)
    new_gpu_free, new_numa_free = resv.gpu_free, resv.numa_free
    if gpu_take is not None and gpu_per_inst is not None:
        g_upd = (gpu_take[:, :, None] * gpu_per_inst[:, None, :]
                 * consuming[:, None, None])
        new_gpu_free = jnp.maximum(
            resv.gpu_free.at[tgt].add(-g_upd, mode="drop"), 0.0)
    if numa_take is not None:
        new_numa_free = jnp.maximum(
            resv.numa_free.at[tgt].add(
                -numa_take * consuming[:, None, None], mode="drop"), 0.0)
    return resv.replace(
        free=new_free,
        gpu_free=new_gpu_free,
        numa_free=new_numa_free,
        valid=resv.valid & ~exhausted)
