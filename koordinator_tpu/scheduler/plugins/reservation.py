"""Reservation plugin: batched restore/consume semantics.

Behavior parity with plugins/reservation/ (SURVEY.md 2.1):
- A Reservation is scheduled ahead of time as a "reserve pod", so its full
  allocatable is already counted in node `requested`
  (transformer.go restoreUnmatchedReservations comment: reservations and
  consuming pods would otherwise be cumulative; the net accounting keeps
  exactly the reservation's allocatable charged).
- When a pending pod matches a reservation's owners, the reserved capacity
  is effectively returned to the pod's view of the node
  (transformer.go:240 restoreMatchedReservation), the nominator picks the
  reservation to consume, and Reserve allocates from it — so a consuming
  pod does NOT increase node `requested` for the covered portion
  (plugin.go:521-613).
- AllocateOnce reservations admit a single consumer and are then exhausted
  (plugin.go:509-510).

Batched TPU design: reservations are rare (V small), so each reservation
slot becomes a VIRTUAL NODE column appended to the score/feasibility
matrices inside the normal commit rounds. The slot column's capacity is the
reservation's remaining free; only owner-matched pods see it (the restore +
nominate semantics); its score is MaxNodeScore so owners prefer it (the
nominator's reservation preference). Because slots ride the same
priority-ordered prefix gates as real nodes and quota levels, consumer
admission interleaves EXACTLY with normal pods — no separate pre-pass, no
priority inversion against non-consumers. AllocateOnce is a per-slot
single-winner gate inside the inner commit.

Documented deviation: the reference lets one pod straddle reservation +
node free capacity; here a pod either fits entirely within the reservation
free or schedules as a normal pod (conservative — the reserved capacity
stays charged to the node either way).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch, ReservationState


def slot_columns(snap: ClusterSnapshot, pods: PodBatch,
                 static_ok: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Virtual-node columns for the V reservation slots.

    Returns (slot_ok [P, V], slot_alloc [V, R], slot_node i32[V]):
    - slot_ok: pod may consume slot v — owner match (transformer.go
      matched-owner restore) AND the slot's underlying node passes the
      pod's round-invariant gates (Filter still applies on that node);
      NUMA-bound and device-requesting pods are excluded (reserved cpusets
      / reserved device instances not modeled yet — those pods schedule on
      real nodes, conservatively leaving reserved capacity charged).
    - slot_alloc: the slot's capacity = remaining reserved free.
    - slot_node: underlying real node per slot (-1 invalid).
    """
    from koordinator_tpu.scheduler.plugins import deviceshare

    resv = snap.reservations
    node_c = jnp.maximum(resv.node, 0)
    base_ok = (resv.valid & (resv.node >= 0))[None, :]           # [1, V]
    owner_ok = ((pods.reservation_owner[:, None] >= 0)
                & (pods.reservation_owner[:, None]
                   == resv.owner_group[None, :]))                # [P, V]
    slot_ok = (base_ok & owner_ok & static_ok[:, node_c]
               & ~pods.numa_single[:, None]
               & ~deviceshare.has_device_request(pods)[:, None])
    return slot_ok, resv.free, resv.node


def rebuild_reservations(resv: ReservationState, pods: PodBatch,
                         res_slot: jnp.ndarray,
                         ok: jnp.ndarray) -> ReservationState:
    """Final reservation state from the surviving assignment (pods the gang
    Permit barrier revoked give their reserved capacity back)."""
    n_res = resv.valid.shape[0]
    consuming = ok & (res_slot >= 0)
    tgt = jnp.where(consuming, res_slot, n_res)
    consumed = jnp.zeros_like(resv.free).at[tgt].add(
        pods.requests * consuming[:, None], mode="drop")
    took_once = jnp.zeros((n_res,), bool).at[tgt].max(
        consuming, mode="drop")
    new_free = jnp.where((resv.allocate_once & took_once)[:, None],
                         0.0, jnp.maximum(resv.free - consumed, 0.0))
    return resv.replace(free=new_free,
                        valid=resv.valid & ~(resv.allocate_once & took_once))
