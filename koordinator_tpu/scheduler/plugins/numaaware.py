"""NodeNUMAResource plugin: batched NUMA-zone fit, scoring, and zone
selection kernels.

Behavior parity with plugins/nodenumaresource/ (SURVEY.md 2.1):
- Pods that require CPU binding / single-NUMA-node placement
  (`PodBatch.numa_single`, the resource-spec annotation + LSR/LSE QoS) must
  fit entirely within one NUMA zone of the node (topology_hint.go hint
  generation merged under the SingleNUMANode policy).
- Zone choice follows the NUMAAllocateStrategy (least_allocated.go /
  most_allocated.go): MostAllocated packs the fullest fitting zone,
  LeastAllocated spreads to the freest.
- Score mirrors scoring.go resourceAllocationScorer (least/most allocated)
  restricted to the zone the pod would take.

TPU design: zone capacity/usage live as [N, Z, 2] (cpu milli, mem MiB)
columns; the hint-merge loop becomes an argmax over the zone axis, and
sequential-exactness of concurrent zone commits reuses the segment prefix
gate with flattened (node, zone) segment ids. The exact per-core cpuset
assignment (cpu_accumulator.go takeCPUs) is bind-time per-pod work on the
chosen node only — that stays on host (numa_cpu_accumulator.py), exactly
like the reference runs it in Reserve, not in the Filter/Score hot loop.

Known deviation: pods consuming a Reservation skip zone accounting (the
reference supports reserved cpusets; tracked for a later round).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from koordinator_tpu.api.extension import ResourceKind
from koordinator_tpu.scheduler.batching import EPS, MAX_NODE_SCORE
from koordinator_tpu.snapshot.schema import NodeState, PodBatch, shape_contract

CPU = int(ResourceKind.CPU)
MEM = int(ResourceKind.MEMORY)


@shape_contract(pods="PodBatch", _returns="f32[P~pad:zero,2]",
                _pad="zero rows for unbound pods (their scatters no-op)")
def pod_zone_requests(pods: PodBatch) -> jnp.ndarray:
    """f32[P, 2]: the (cpu milli, mem MiB) a NUMA-bound pod takes from its
    zone; zero rows for unbound pods so their scatters are no-ops."""
    req2 = jnp.stack([pods.requests[:, CPU], pods.requests[:, MEM]], axis=-1)
    return req2 * pods.numa_single[:, None]


@shape_contract(nodes="NodeState", pods="PodBatch",
                _returns="bool[P~pad:one,N~pad:any]",
                _pad="non-NUMA-bound pods pass everywhere; invalid "
                     "zones (numa_valid False) never fit")
def zone_prefilter(nodes: NodeState, pods: PodBatch) -> jnp.ndarray:
    """bool[P, N]: an upper-bound single-NUMA fit against the batch-start
    zone state (free only shrinks during commit, so this is a sound
    prefilter; the exact gate runs per inner commit step on the chosen
    node). Non-NUMA-bound pods pass everywhere."""
    req2 = pod_zone_requests(pods)                      # [P, 2]
    free = nodes.numa_free                              # [N, Z, 2]
    fits = jnp.all(free[None] + EPS >= req2[:, None, None, :], axis=-1)
    fits &= nodes.numa_valid[None]                      # [P, N, Z]
    ok = jnp.any(fits, axis=-1)
    return ok | ~pods.numa_single[:, None]


@shape_contract(nodes="NodeState", pods="PodBatch",
                _returns="f32[P~pad:zero,N~pad:any]",
                _pad="0 for unbound pods and nodes without topology")
def numa_score_matrix(nodes: NodeState, pods: PodBatch,
                      strategy: str = "most") -> jnp.ndarray:
    """f32[P, N] in [0, 100]: allocation score of the zone the pod would
    take, 0 for unbound pods / nodes without topology.

    Mirrors scoring.go least/mostResourceScorer over the zone's cpu+mem.
    Computed once per batch from the snapshot state (heuristic preference;
    capacity exactness is enforced by the commit prefix gates).
    """
    req2 = pod_zone_requests(pods)                      # [P, 2]
    cap = nodes.numa_cap                                # [N, Z, 2]
    free = nodes.numa_free
    fits = jnp.all(free[None] + EPS >= req2[:, None, None, :], axis=-1)
    fits &= nodes.numa_valid[None]                      # [P, N, Z]
    used_after = cap[None] - free[None] + req2[:, None, None, :]
    frac = used_after / jnp.maximum(cap[None], 1e-9)    # [P, N, Z, 2]
    if strategy == "most":
        zone_score = jnp.mean(frac, axis=-1)
    else:
        zone_score = jnp.mean(1.0 - frac, axis=-1)
    zone_score = jnp.where(fits, zone_score, -1.0)
    best = jnp.max(zone_score, axis=-1)                 # [P, N]
    score = jnp.clip(best, 0.0, 1.0) * MAX_NODE_SCORE
    return jnp.where(pods.numa_single[:, None], score, 0.0)


# Zone choice and zone capacity gating moved to the topology-manager merge
# (scheduler/topologymanager.py resolve + greedy_take): the single-NUMA
# case is the SingleNUMANode policy with the CPU/mem provider, so there is
# one hint/affinity path for all four policies.
