"""Shared batched-commit primitives: priority ranking and the sort-free
segment prefix gate used by every sequential-equivalent commit kernel
(node capacity, quota levels, reservations).

Split out of core.py so plugin kernels (reservation pre-pass, device
allocator) can reuse them without a circular import.
"""

from __future__ import annotations

import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import PodBatch

EPS = 0.5  # comparison tolerance in canonical units (millicores / MiB)
MAX_NODE_SCORE = 100.0  # framework.MaxNodeScore — single source of truth;
                        # the reservation-slot preference (3*MAX_NODE_SCORE+1
                        # in core.py) relies on every plugin score topping
                        # out at this value and at most THREE plugin scores
                        # (loadaware + numa + device) summing per node —
                        # raise the slot multiplier when adding a fourth


def stable_rank(key: jnp.ndarray) -> jnp.ndarray:
    """i32[P]: each element's position in the stable ascending sort of
    `key` (ties keep index order). One sort + one scatter; shared by the
    priority ranking and the straggler-tail compaction (whose budgeted
    selection admits the first K candidates of a ranking without
    materializing the sorted array)."""
    p = key.shape[0]
    order = jnp.argsort(key, stable=True)
    return jnp.zeros((p,), jnp.int32).at[order].set(
        jnp.arange(p, dtype=jnp.int32))


def rank_by_priority(pods: PodBatch) -> jnp.ndarray:
    """i32[P]: position in scheduling order — priority desc, index asc.

    The batched analogue of the scheduler queue order (Coscheduling Less +
    default PrioritySort); gang-group batching is handled by the caller.
    """
    return stable_rank(-pods.priority)


def segment_prefix_ok(seg: jnp.ndarray, earlier: jnp.ndarray,
                      req: jnp.ndarray, base_used: jnp.ndarray,
                      limit: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    """Does each pod fit its segment's limit when charged after all
    earlier-ranked pods of the same segment?

    bool[P]: base_used[seg] + Σ req of same-segment earlier pods + own req
    <= limit[seg]. Computed sort-free as a masked [P,P] x [P,R] matmul —
    TPU sorts cost ~1.5ms for even tiny arrays while the MXU does this
    contraction in microseconds. `earlier[p, p'] = rank[p'] < rank[p]` is
    shared across all segment levels of a commit step. Out-of-range
    segments (>= num_segments, the "no candidate" encoding) are vacuously
    OK; their req rows are zeroed by the caller.
    """
    same = seg[:, None] == seg[None, :]                         # [P, P]
    mask = (same & earlier).astype(req.dtype)
    cum_excl = mask @ req                                       # [P, R]
    seg_c = jnp.clip(seg, 0, num_segments - 1)
    ok = jnp.all(base_used[seg_c] + cum_excl + req <= limit[seg_c] + EPS,
                 axis=-1)
    return ok | (seg >= num_segments)
