"""The batched scheduling core: feasibility → score → conflict-resolving
commit, all inside one jitted program.

Replaces the reference's per-pod scheduling cycle (SURVEY.md 3.1,
k8s scheduleOne + frameworkext transformers):

- HOT LOOP #1 (Filter, parallel over nodes) -> a fused [P, N] feasibility
  mask: node schedulable + resource fit + nodeSelector gate + LoadAware
  usage gate + ElasticQuota admission + gang quorum.
- HOT LOOP #2 (Score) -> the LoadAware [P, N] score matrix.
- selectHost + assume + Permit + Bind -> `num_rounds` commit rounds inside
  lax.scan. Each round every unplaced pod picks its best node (argmax = the
  top-k reduce); conflicts on a node are resolved in pod-priority order by a
  sorted segment prefix-sum (the batched equivalent of sequential assume),
  quota admission is enforced per tree level the same way, accepted pods
  scatter their requests/estimates into the carried node and quota tensors,
  and losers retry next round against updated state. Strict gangs that miss
  minMember by the end of the batch are rolled back (Permit barrier,
  coscheduling core.go:311-341).

Sequential-equivalence note: within a round, an accepted pod's effect on the
*scores* of later pods lands at the next round boundary (its effect on
capacity is exact via the prefix sums). With num_rounds >= 2 this matches the
reference's assume semantics at batch granularity; per-pod equivalence is
recovered with chunk size 1 (golden tests do both).

Float note: capacities are float32 in millicores/MiB; prefix sums over a
100k-pod chunk keep absolute error well under one millicore/MiB at realistic
magnitudes, and comparisons use a 0.5-unit tolerance, conservative on the
safe (no-overcommit) side.
"""

from __future__ import annotations

import functools
from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp

from koordinator_tpu.scheduler.batching import (
    EPS,
    rank_by_priority,
    segment_prefix_ok,
)
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.scheduler.plugins.reservation import (
    MAX_NODE_SCORE,
    rebuild_reservations,
    reservation_prepass,
)
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    PodBatch,
)


@flax.struct.dataclass
class ScheduleResult:
    assignment: jnp.ndarray      # i32[P] node index, -1 = unschedulable
    chosen_score: jnp.ndarray    # f32[P] score of the chosen node (debug)
    snapshot: ClusterSnapshot    # post-commit snapshot (requested/used updated)


@functools.partial(jax.jit, static_argnames=("num_rounds", "k_choices",
                                             "score_dims", "approx_topk",
                                             "tie_break"))
def schedule_batch(snap: ClusterSnapshot, pods: PodBatch,
                   cfg: loadaware.LoadAwareConfig,
                   num_rounds: int = 4, k_choices: int = 8,
                   score_dims: tuple = None,
                   approx_topk: bool = False,
                   tie_break: bool = False) -> ScheduleResult:
    """Schedule a pod batch against the snapshot. Pure function; the caller
    publishes `result.snapshot` as the next version (store.update)."""
    nodes0, quotas0, gangs0 = snap.nodes, snap.quotas, snap.gangs
    n_nodes = nodes0.num_nodes
    n_quotas = quotas0.min.shape[0]
    n_gangs = gangs0.min_member.shape[0]
    p = pods.num_pods

    rank = rank_by_priority(pods)
    # rank[p'] < rank[p], shared by every prefix gate in the commit
    earlier = rank[None, :] < rank[:, None]                      # [P, P]

    # --- static (per-batch) gates -------------------------------------------
    # nodeSelector gate: sel_match[sel_id, label_group[n]]
    sel = jnp.maximum(pods.selector_id, 0)
    sel_ok = (pods.selector_id[:, None] < 0) | \
        pods.selector_match[sel][:, nodes0.label_group]          # [P, N]
    # gang quorum (PreFilter, coscheduling core/core.go:220-274)
    gid = jnp.maximum(pods.gang_id, 0)
    gang_quorum = (gangs0.member_count >= gangs0.min_member) & gangs0.valid
    gang_ok = (pods.gang_id < 0) | gang_quorum[gid]              # [P]

    quota_id = jnp.maximum(pods.quota_id, 0)
    # ancestor chain per pod per depth, -1 = none
    pod_anc = jnp.where(pods.quota_id[:, None] >= 0,
                        quotas0.depth_ancestor[quota_id], -1)    # [P, D]

    # LoadAware filter is round-invariant: it reads only NodeMetric-derived
    # columns and thresholds, never assume state (load_aware.go:123-254
    # touches no NodeInfo.requested), so compute it once for the batch.
    la_ok = loadaware.filter_mask(nodes0, pods, cfg)
    static_ok = la_ok & sel_ok & nodes0.schedulable[None, :]     # [P, N]

    # --- reservation restore/consume pre-pass (transformer.go:240-291) ------
    # Matching pods consume reserved capacity (already counted in node
    # `requested`) in exact priority order; they skip the normal rounds.
    res_placed, res_slot, quota_used0 = reservation_prepass(
        snap, pods, static_ok, earlier, pod_anc, gang_ok)

    def round_body(carry, _):
        requested, quota_used, assigned_est, prod_assigned_est, \
            gang_placed, placed, out_score = carry
        active = pods.valid & (placed < 0) & gang_ok

        nodes = nodes0.replace(
            requested=requested,
            assigned_estimated=assigned_est,
            prod_assigned_estimated=prod_assigned_est)

        # --- feasibility [P, N] (HOT LOOP #1) ---
        fit = jnp.all(pods.requests[:, None, :] + requested[None]
                      <= nodes.allocatable[None] + EPS, axis=-1)
        feasible = fit & static_ok & active[:, None]

        # quota admission (ElasticQuota PreFilter, plugin.go:211-257):
        # used + request <= runtime at every tree level
        quota_admit = jnp.ones((p,), bool)
        for d in range(MAX_QUOTA_DEPTH):
            anc = pod_anc[:, d]
            a = jnp.maximum(anc, 0)
            level_ok = jnp.all(quota_used[a] + pods.requests
                               <= quotas0.runtime[a] + EPS, axis=-1)
            quota_admit &= (anc < 0) | level_ok
        feasible &= quota_admit[:, None]

        # --- score [P, N] (HOT LOOP #2) + top-k select ---
        # The [P, N] matrices are computed ONCE per round; the commit then
        # runs k cheap [P]-sized inner steps in which every rejected pod
        # falls through to its next-best node. Within a round the LoadAware
        # inputs are frozen (the reference's NodeMetric does not change on
        # assume either); capacity and quota stay exact via prefix sums.
        scores = loadaware.score_matrix(nodes, pods, cfg, score_dims)
        if tie_break:
            # k8s selectHost picks uniformly among max-score nodes
            # (schedule_one.go reservoir sample); a deterministic per-
            # (pod, node) jitter < 0.5 reproduces that spread without
            # reordering distinct integer scores, and de-clusters the
            # batched argmax under contention.
            pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
            ni = jnp.arange(n_nodes, dtype=jnp.uint32)[None, :]
            h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & 1023
            scores = scores + h.astype(jnp.float32) * (0.49 / 1024.0)
        masked = jnp.where(feasible, scores, -1.0)
        k = min(k_choices, n_nodes)
        if approx_topk:
            # TPU-optimized partial reduction (approx_max_k) — the choice
            # list is a heuristic preference order, so bounded recall only
            # means an occasional pod falls to a later round.
            topk_val, topk_idx = jax.lax.approx_max_k(masked, k)
        else:
            topk_val, topk_idx = jax.lax.top_k(masked, k)
        topk_idx = topk_idx.astype(jnp.int32)

        def inner(inner_carry, _):
            requested, quota_used, placed, kptr, out_score = inner_carry
            val = jnp.take_along_axis(topk_val, kptr[:, None], 1)[:, 0]
            choice = jnp.take_along_axis(topk_idx, kptr[:, None], 1)[:, 0]
            trying = active & (placed < 0) & (kptr < k) & (val > -0.5)
            choice_eff = jnp.where(trying, choice, n_nodes)

            # node capacity prefix in priority order
            eff_req = jnp.where(trying[:, None], pods.requests, 0.0)
            accept = trying & segment_prefix_ok(
                choice_eff, earlier, eff_req, requested,
                nodes.allocatable, n_nodes)

            # quota prefix per tree level, same trick
            for d in range(MAX_QUOTA_DEPTH):
                anc = jnp.where(accept, pod_anc[:, d], -1)
                anc_eff = jnp.where(anc >= 0, anc, n_quotas)
                acc_req = jnp.where(accept[:, None], pods.requests, 0.0)
                accept &= segment_prefix_ok(
                    anc_eff, earlier, acc_req, quota_used,
                    quotas0.runtime, n_quotas)

            # scatter-commit (assume; scheduler_adapter assume/forget)
            acc_req = pods.requests * accept[:, None]
            requested = requested.at[choice_eff].add(acc_req, mode="drop")
            for d in range(MAX_QUOTA_DEPTH):
                anc = jnp.where(accept, pod_anc[:, d], -1)
                quota_used = quota_used.at[
                    jnp.where(anc >= 0, anc, n_quotas)].add(acc_req,
                                                            mode="drop")
            placed = jnp.where(accept, choice, placed)
            out_score = jnp.where(accept, val, out_score)
            # a rejected pod's chosen node just filled up: fall through
            kptr = jnp.where(trying & ~accept, kptr + 1, kptr)
            return (requested, quota_used, placed, kptr, out_score), None

        (requested, quota_used, placed, _, out_score), _ = jax.lax.scan(
            inner,
            (requested, quota_used, placed, jnp.zeros((p,), jnp.int32),
             out_score),
            None, length=k)

        # register newly placed pods' estimates for the next round's scores
        new = (placed >= 0) & active
        tgt = jnp.where(new, placed, n_nodes)
        est = pods.estimated * new[:, None]
        assigned_est = assigned_est.at[tgt].add(est, mode="drop")
        is_prod = pods.priority_class == 4  # PriorityClass.PROD
        prod_assigned_est = prod_assigned_est.at[tgt].add(
            est * is_prod[:, None], mode="drop")
        gang_placed = gang_placed.at[jnp.where(new & (pods.gang_id >= 0),
                                               pods.gang_id, n_gangs)].add(
            1, mode="drop")
        return (requested, quota_used, assigned_est, prod_assigned_est,
                gang_placed, placed, out_score), None

    # Seed the round carry with the reservation pre-pass result: consuming
    # pods are already placed (node requested unchanged — covered capacity
    # was pre-charged), their estimates feed the next scores (podAssignCache
    # tracks reservation consumers too), and they count toward gang quorum.
    res_ok = res_placed >= 0
    res_tgt = jnp.where(res_ok, res_placed, n_nodes)
    res_est = pods.estimated * res_ok[:, None]
    is_prod0 = pods.priority_class == 4  # PriorityClass.PROD
    init = (
        nodes0.requested,
        quota_used0,
        nodes0.assigned_estimated.at[res_tgt].add(res_est, mode="drop"),
        nodes0.prod_assigned_estimated.at[res_tgt].add(
            res_est * is_prod0[:, None], mode="drop"),
        jnp.zeros((n_gangs,), jnp.int32).at[
            jnp.where(res_ok & (pods.gang_id >= 0), pods.gang_id,
                      n_gangs)].add(1, mode="drop"),
        res_placed,
        jnp.where(res_ok, MAX_NODE_SCORE, -1.0).astype(jnp.float32))
    (_, _, _, _, gang_placed, placed, out_score), _ = jax.lax.scan(
        round_body, init, None, length=num_rounds)

    # --- gang all-or-nothing rollback (Permit barrier, core.go:311-341) ---
    gang_total = gangs0.assumed + gang_placed
    gang_fail = (gangs0.valid & gangs0.strict
                 & (gang_total < gangs0.min_member))
    gid = jnp.maximum(pods.gang_id, 0)
    revoke = (placed >= 0) & (pods.gang_id >= 0) & gang_fail[gid]
    placed = jnp.where(revoke, -1, placed)

    # --- rebuild post-commit state from the final assignment --------------
    ok = placed >= 0
    tgt = jnp.where(ok, placed, n_nodes)
    fin_req = pods.requests * ok[:, None]
    fin_est = pods.estimated * ok[:, None]
    is_prod = pods.priority_class == 4
    # reservation consumers don't grow node requested (covered capacity was
    # already charged by the reserve pod, plugin.go:521-613)
    node_req = fin_req * (res_slot < 0)[:, None]
    requested = nodes0.requested.at[tgt].add(node_req, mode="drop")
    assigned_est = nodes0.assigned_estimated.at[tgt].add(fin_est, mode="drop")
    prod_assigned_est = nodes0.prod_assigned_estimated.at[tgt].add(
        fin_est * is_prod[:, None], mode="drop")
    quota_used = quotas0.used
    for d in range(MAX_QUOTA_DEPTH):
        anc = jnp.where(ok, pod_anc[:, d], -1)
        quota_used = quota_used.at[jnp.where(anc >= 0, anc, n_quotas)].add(
            fin_req, mode="drop")
    gang_assumed = gangs0.assumed.at[jnp.where(ok & (pods.gang_id >= 0),
                                               pods.gang_id, n_gangs)].add(
        1, mode="drop")

    chosen_score = jnp.where(ok, out_score, -1.0)
    new_snap = snap.replace(
        nodes=nodes0.replace(requested=requested,
                             assigned_estimated=assigned_est,
                             prod_assigned_estimated=prod_assigned_est),
        quotas=quotas0.replace(used=quota_used),
        gangs=gangs0.replace(assumed=gang_assumed),
        reservations=rebuild_reservations(snap.reservations, pods,
                                          res_slot, ok),
        version=snap.version + 1,
    )
    return ScheduleResult(assignment=placed, chosen_score=chosen_score,
                          snapshot=new_snap)
