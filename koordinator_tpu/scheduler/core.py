"""The batched scheduling core: feasibility → score → conflict-resolving
commit, all inside one jitted program.

Replaces the reference's per-pod scheduling cycle (SURVEY.md 3.1,
k8s scheduleOne + frameworkext transformers):

- HOT LOOP #1 (Filter, parallel over nodes) -> a fused [P, N] feasibility
  mask: node schedulable + resource fit + nodeSelector gate + LoadAware
  usage gate + ElasticQuota admission + gang quorum.
- HOT LOOP #2 (Score) -> the LoadAware [P, N] score matrix.
- selectHost + assume + Permit + Bind -> `num_rounds` commit rounds inside
  lax.scan. Each round every unplaced pod picks its best node (argmax = the
  top-k reduce); conflicts on a node are resolved in pod-priority order by a
  sorted segment prefix-sum (the batched equivalent of sequential assume),
  quota admission is enforced per tree level the same way, accepted pods
  scatter their requests/estimates into the carried node and quota tensors,
  and losers retry next round against updated state. Strict gangs that miss
  minMember by the end of the batch are rolled back (Permit barrier,
  coscheduling core.go:311-341).
- Reservations ride the same machinery as VIRTUAL NODE columns (owner-
  restricted, capacity = reserved free, MaxNodeScore preference), so
  consumer admission interleaves exactly with normal pods across the node/
  quota/NUMA prefix gates (plugins/reservation.py).
- NUMA-bound pods additionally pass a zone-level prefix gate and commit
  into zone usage (plugins/numaaware.py).

Sequential-equivalence note: within a round, an accepted pod's effect on the
*scores* of later pods lands at the next round boundary (its effect on
capacity is exact via the prefix sums). With num_rounds >= 2 this matches the
reference's assume semantics at batch granularity; per-pod equivalence is
recovered with chunk size 1 (golden tests do both).

Float note: capacities are float32 in millicores/MiB; prefix sums over a
100k-pod chunk keep absolute error well under one millicore/MiB at realistic
magnitudes, and comparisons use a 0.5-unit tolerance, conservative on the
safe (no-overcommit) side.
"""

from __future__ import annotations

import functools
from typing import Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.scheduler.batching import (
    EPS,
    rank_by_priority,
    segment_prefix_ok,
    stable_rank,
)
from koordinator_tpu import obs
from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.scheduler import topologymanager
from koordinator_tpu.scheduler.cascade import stage1_mask, static_gates
from koordinator_tpu.scheduler.plugins import deviceshare, loadaware, numaaware
from koordinator_tpu.scheduler.plugins.numaaware import CPU as CPU_KIND, MEM as MEM_KIND
from koordinator_tpu.scheduler.plugins.reservation import (
    MAX_NODE_SCORE,
    rebuild_reservations,
    slot_columns,
)
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    NUM_AUX_TYPES,
    NUM_DEV_DIMS,
    PER_POD_FIELDS,
    PodBatch,
    register_struct,
    shape_contract,
)


# ScheduleResult fields indexed by pod row — a caller that reorders the
# batch (prefix packing) must inverse-permute exactly these
PER_POD_RESULT_FIELDS = ("assignment", "chosen_score", "numa_zone",
                         "numa_take", "gpu_take", "aux_inst", "res_slot")


@flax.struct.dataclass
class ScheduleResult:
    assignment: jnp.ndarray      # i32[P] node index, -1 = unschedulable
    chosen_score: jnp.ndarray    # f32[P] score of the chosen node (debug)
    numa_zone: jnp.ndarray       # i32[P] zone taken by NUMA-bound pods, -1
                                 # (feeds the resource-status annotation /
                                 # host cpuset accumulator at bind time)
    numa_take: jnp.ndarray       # f32[P, Z, 2] per-zone (cpu, mem) actually
                                 # charged by topology-engaged pods — multi-
                                 # zone under best-effort/restricted policy
                                 # (resource_manager.go NUMANodeResources)
    gpu_take: jnp.ndarray        # bool[P, I] GPU instances taken on the
                                 # assigned node (feeds the device-allocation
                                 # annotation at bind, plugin.go PreBind)
    aux_inst: jnp.ndarray        # i32[P, A] aux (rdma/fpga) instance, -1
    res_slot: jnp.ndarray        # i32[P] reservation slot consumed, -1 —
                                 # feeds the reservation-allocated
                                 # annotation at bind and the forget path
    gang_failed: jnp.ndarray     # bool[G] strict gangs PROVEN below quorum
                                 # this batch (no members outstanding) —
                                 # members assumed in EARLIER batches still
                                 # hold capacity; the host reclaims them
                                 # through the forget/un-assume path without
                                 # waiting for the Permit timeout
    snapshot: ClusterSnapshot    # post-commit snapshot (requested/used updated)
    amplified: bool = flax.struct.field(pytree_node=False, default=False)
    # ^ whether the amplified-CPU gates produced this result; the forget/
    #   un-assume path MUST mirror it so returned CPU equals charged CPU


register_struct(ScheduleResult, {
    "assignment": "i32[P~pad:-1]",
    "chosen_score": "f32[P~pad:-1]",  # pad rows are never placed
    "numa_zone": "i32[P~pad:-1]",
    "numa_take": "f32[P~pad:zero,Z~pad:zero,2]",
    "gpu_take": "bool[P~pad:false,I~pad:false]",
    "aux_inst": "i32[P~pad:-1,AX]",
    "res_slot": "i32[P~pad:-1]",
    "gang_failed": "bool[G~pad:false]",
    "snapshot": "ClusterSnapshot",
})


@shape_contract(
    snap="ClusterSnapshot", pods="PodBatch", cfg="LoadAwareConfig",
    _returns="ScheduleResult",
    _static={"num_rounds": 2, "k_choices": 2, "quota_depth": 2},
    _pad="pods.valid masks padded pod rows (assignment -1); "
         "nodes.schedulable masks padded node columns; every "
         "[P]-leading result field is -1/0/False for unplaced rows")
@functools.partial(jax.jit, static_argnames=("num_rounds", "k_choices",
                                             "score_dims", "approx_topk",
                                             "tie_break", "enable_numa",
                                             "numa_strategy",
                                             "enable_devices",
                                             "device_strategy",
                                             "quota_depth",
                                             "fit_dims",
                                             "enable_amplification",
                                             "topo_prefix",
                                             "dom_classes",
                                             "numa_prefix",
                                             "gpu_prefix",
                                             "cascade"))
def schedule_batch(snap: ClusterSnapshot, pods: PodBatch,
                   cfg: loadaware.LoadAwareConfig,
                   num_rounds: int = 4, k_choices: int = 8,
                   score_dims: tuple = None,
                   approx_topk: bool = False,
                   tie_break: bool = False,
                   enable_numa: bool = True,
                   numa_strategy: str = "most",
                   enable_devices: bool = True,
                   device_strategy: str = "least",
                   quota_depth: int = MAX_QUOTA_DEPTH,
                   fit_dims: tuple = None,
                   enable_amplification: bool = False,
                   topo_prefix: int = None,
                   dom_classes: tuple = None,
                   numa_prefix: int = None,
                   gpu_prefix: int = None,
                   cascade: bool = False) -> ScheduleResult:
    """Schedule a pod batch against the snapshot. Pure function; the caller
    publishes `result.snapshot` as the next version (store.update).

    `fit_dims`: static tuple of ResourceKind indices the capacity/quota
    gates check; None = all dims. k8s noderesources.Fit only evaluates the
    resources a pod requests, so restricting to the union of dims any pod
    in the workload uses is semantically faithful and skips dead matmul
    columns (the scatter-commits always update the full R axis).

    `topo_prefix` (static): PACKING CONTRACT — when set, every pod with any
    spread/anti/aff membership or carried term sits in batch rows
    [0, topo_prefix). The per-group same-domain [P, P] prefix machinery and
    the (pod x group) gate matmuls then run on [topo_prefix, ...] slices —
    the dominant inner-commit cost on constraint-sparse workloads shrinks
    quadratically (~16x at the default bench shapes) with bit-identical
    results. The caller MUST enforce the contract host-side
    (synthetic.pack_topo_prefix validates; the bench tail masks overflow
    pods to a later pass): a member outside the prefix silently drops out
    of ALL in-batch topology accounting — the in-step gates and the
    round-level counts alike. None = full width (every row gated; no
    contract).

    `dom_classes` (static): DOMAIN-CLASS CONTRACT — groups sharing an
    upstream topologyKey have IDENTICAL rows in their domain matrix, so
    their in-step same-domain masks are equal. A 3-tuple
    (spread_classes, anti_classes, aff_classes), each a tuple of
    group-id tuples partitioning that family's groups into equal-row
    classes: the inner commit then builds ONE mask per class and
    batches the per-group matvecs into a single [pc, pc] x [pc, Gc]
    matmul — group-count-independent cost. The sums are 0/1 floats, so
    batching is bit-identical to the per-group loop. Callers derive
    classes host-side from the actual domain rows
    (synthetic.dom_classes); a class containing groups with UNEQUAL
    rows silently mis-gates. None = every group its own class (the
    reference per-group behavior).

    `numa_prefix` / `gpu_prefix` (static): further packing contracts in
    the same spirit as topo_prefix (synthetic.pack_gate_prefixes
    establishes all three at once). numa_prefix: every CPU-bind
    (numa_single) pod sits below it AND no node in the snapshot carries
    a topology-manager policy (numa_policy == NONE everywhere — with a
    policy node, ANY pod choosing it engages the manager and the
    prefix is invalid; such callers must leave numa_prefix=None).
    gpu_prefix: every device-requesting pod (deviceshare.
    has_device_request) sits below it. The per-inner-step topology-
    manager machinery and zone prefix gates then run on numa_prefix
    rows, and the GPU instance gates on gpu_prefix rows.

    `cascade` (static): the Filter->Score gate cascade
    (scheduler/cascade.py). Stage 1 folds a cheap candidate mask —
    batch-start resource fit + quota ceilings on top of the static
    gates — into the node columns; stage 2 narrows the HEAVY per-pair
    batch gates (device prefilter/score [P, N, I], zone prefilter/score
    [P, N, Z], policy combined-fit) to the numa_prefix / gpu_prefix
    rows, padding pass-through rows back in. Both layers are placement-
    preserving (monotone batch-start state; the prefix contracts), so
    cascade=False — the default, and the conformance oracle — produces
    bit-for-bit identical results (tests/test_cascade.py)."""
    nodes0, quotas0, gangs0 = snap.nodes, snap.quotas, snap.gangs
    devices0 = snap.devices
    n_nodes = nodes0.num_nodes
    n_quotas = quotas0.min.shape[0]
    n_gangs = gangs0.min_member.shape[0]
    p = pods.num_pods
    # device pools are skipped entirely when the snapshot has no instance
    # capacity (static shapes, so this specializes the compiled program)
    n_inst = devices0.gpu_free.shape[1]
    n_aux = devices0.aux_free.shape[2]
    use_gpu = enable_devices and n_inst > 0
    use_aux = enable_devices and n_aux > 0

    fd = list(fit_dims) if fit_dims is not None else None

    def dims(x):
        """Restrict a [..., R] operand to the checked resource dims."""
        return x if fd is None else x[..., fd]

    # constrained-prefix width for the topology families (see docstring);
    # pc == p (the default) keeps every slice full-width and the tail
    # concatenations zero-size — one code path for both modes
    pc = p if topo_prefix is None else max(min(int(topo_prefix), p), 0)
    pn = p if numa_prefix is None else max(min(int(numa_prefix), p), 0)
    pg = p if gpu_prefix is None else max(min(int(gpu_prefix), p), 0)

    rank = rank_by_priority(pods)
    # rank[p'] < rank[p], shared by every prefix gate in the commit
    earlier = rank[None, :] < rank[:, None]                      # [P, P]

    # --- static (per-batch) gates — stage 1 of the gate cascade ------------
    # gang quorum (PreFilter, coscheduling core/core.go:220-274); a
    # match-policy-satisfied gang short-circuits the quorum check — its
    # members schedule individually (core.go:236 OnceSatisfied fast path)
    gid = jnp.maximum(pods.gang_id, 0)
    gang_quorum = ((gangs0.member_count >= gangs0.min_member)
                   | gangs0.satisfied) & gangs0.valid
    gang_ok = (pods.gang_id < 0) | gang_quorum[gid]              # [P]

    quota_id = jnp.maximum(pods.quota_id, 0)
    # ancestor chain per pod per depth, -1 = none
    pod_anc = jnp.where(pods.quota_id[:, None] >= 0,
                        quotas0.depth_ancestor[quota_id], -1)    # [P, D]

    # nodeSelector + round-invariant LoadAware filter + schedulable +
    # taint forbids/penalty: one shared implementation for both cascade
    # modes (cascade.static_gates — the cheap per-batch node gates)
    static_ok, taint_penalty = static_gates(nodes0, pods, cfg)
    # the slot columns see the gates BEFORE the stage-1 fit mask and the
    # device/NUMA prefilters: those reason about the node's open pools,
    # but a consumer draws from the reservation's own hold (restore
    # semantics)
    static_base = static_ok
    if cascade:
        # stage-1 candidate mask: batch-start resource fit + quota
        # ceilings fold in up front. Placement-preserving: node
        # requested and quota used are monotone within the batch, so
        # every pruned pair would be rejected by the exact round gates
        # anyway (cascade.stage1_mask's contract).
        static_ok = stage1_mask(snap, pods, static_ok,
                                fit_dims=fit_dims, quota_depth=quota_depth)

    def heavy_rows(rows):
        """View of the columns the heavy per-pair batch gates read,
        sliced to a class-prefix width (stage 2 of the cascade): pods
        beyond the numa/gpu packing prefixes cannot engage those gates,
        so their [*, N, Z] / [*, N, I] tensors shrink ~P/rows x."""
        return pods.replace(requests=pods.requests[:rows],
                            gpu_ratio=pods.gpu_ratio[:rows],
                            numa_single=pods.numa_single[:rows])

    def and_rows(mask, gate, rows):
        """AND a [rows, N] gate into the first `rows` rows of `mask`;
        rows beyond pass through (the sliced gate is vacuously True
        there under the packing contract)."""
        return jnp.concatenate([mask[:rows] & gate, mask[rows:]], axis=0)

    # heavy-gate row widths: full width unless the cascade is on AND the
    # corresponding packing contract is established (gpu_prefix /
    # numa_prefix); under the contract the sliced gates are bit-identical
    dev_pg = pg if (cascade and pg < p) else p
    if enable_devices:
        # batch-start device upper bound (exact instance gates run in the
        # inner commit); also rejects device pods on device-less nodes —
        # including ratio-only GPU requests, which don't appear in the
        # node-allocatable columns (deviceshare
        # UnschedulableAndUnresolvable). Runs even with zero instance
        # capacity so such pods never silently place without a GPU.
        with obs.phase(obs_phases.PHASE_STAGE2_DEVICESHARE):
            static_ok = and_rows(
                static_ok,
                deviceshare.prefilter(devices0, heavy_rows(dev_pg)),
                dev_pg)
    if use_gpu:
        with obs.phase(obs_phases.PHASE_STAGE2_DEVICESHARE):
            dev_scores = deviceshare.score_matrix(
                devices0, heavy_rows(dev_pg), device_strategy)
            if dev_pg < p:
                # exact pad: rows beyond pg carry no device request, so
                # their score rows are 0 by construction
                dev_scores = jnp.concatenate(
                    [dev_scores,
                     jnp.zeros((p - dev_pg, n_nodes), dev_scores.dtype)],
                    axis=0)
    numa_used0 = nodes0.numa_cap - nodes0.numa_free              # [N, Z, 2]
    if enable_numa:
        numa_pn = pn if (cascade and pn < p) else p
        # single-NUMA-node prefilter (upper bound; exact gate in the inner
        # commit) + zone-allocation score preference (nodenumaresource
        # topology_hint.go + scoring.go). Under the cascade these run on
        # numa_prefix rows: CPU-bind pods all sit below pn, and the
        # numa_prefix contract guarantees a policy-free snapshot, so
        # rows beyond pass the gates and score 0.
        pods_pn = heavy_rows(numa_pn)
        with obs.phase(obs_phases.PHASE_STAGE2_NUMA):
            static_ok = and_rows(
                static_ok, numaaware.zone_prefilter(nodes0, pods_pn),
                numa_pn)
            numa_scores = numaaware.numa_score_matrix(nodes0, pods_pn,
                                                      numa_strategy)
            if numa_pn < p:
                numa_scores = jnp.concatenate(
                    [numa_scores,
                     jnp.zeros((p - numa_pn, n_nodes), numa_scores.dtype)],
                    axis=0)
        n_zones = nodes0.numa_cap.shape[1]
        # every pod's (cpu, mem) zone demand: on a node whose topology
        # policy engages the manager, ALL pods charge zone usage
        # (resource_manager.go allocates NUMANodeResources per pod), not
        # just the CPU-bind ones
        req2_all = jnp.stack([pods.requests[:, int(CPU_KIND)],
                              pods.requests[:, int(MEM_KIND)]], axis=-1)
        numa_policy0 = nodes0.numa_policy                        # i32[N]
        # policy-node combined-fit prefilter (upper bound): a policy node
        # whose total valid-zone free cannot hold the pod is infeasible
        with obs.phase(obs_phases.PHASE_STAGE2_POLICY):
            total_zfree = jnp.sum(
                nodes0.numa_free * nodes0.numa_valid[:, :, None], axis=1)
            static_ok = and_rows(
                static_ok,
                (numa_policy0 == topologymanager.POLICY_NONE)[None]
                | jnp.all(total_zfree[None] + EPS
                          >= req2_all[:numa_pn, None, :], axis=-1),
                numa_pn)

    # --- reservations as virtual nodes (transformer.go restore/nominate) ---
    # Each reservation slot is an extra owner-restricted column with the
    # slot's remaining free as capacity and MaxNodeScore preference, so
    # consumer admission rides the SAME priority-ordered prefix gates as
    # normal pods (no pre-pass, no priority inversion).
    slot_ok, slot_alloc0, slot_node = slot_columns(snap, pods, static_base)
    n_slots = slot_node.shape[0]
    n_ext = n_nodes + n_slots
    ext_alloc = jnp.concatenate([nodes0.allocatable, slot_alloc0], 0)
    ext_static = jnp.concatenate([static_ok, slot_ok], 1)        # [P, N+V]
    resv0 = snap.reservations
    is_once = resv0.allocate_once                                # bool[V]
    slot_node_c = jnp.maximum(slot_node, 0)

    # --- amplified CPU (nodenumaresource filterAmplifiedCPUs) -------------
    # On a node with amplification ratio > 1 the webhook published
    # AMPLIFIED allocatable; a CPU-bind (exclusive-cpuset) pod's cores cost
    # request x ratio against it, charged amplified at commit so later
    # pods see the true remaining capacity. Zone capacities stay raw:
    # amplifying both the zone resources and the bind-pod zone request by
    # the same ratio (util.go amplifyNUMANodeResources + getResourceOptions)
    # cancels in the fit comparison. Reservation slot columns draw from the
    # reservation's own hold and stay unamplified (documented deviation:
    # the reference amplifies reserved cpusets as reusableResources).
    ci = int(CPU_KIND)
    if enable_amplification:
        amp_ext = jnp.concatenate(
            [nodes0.cpu_amplification,
             jnp.ones((n_slots,), jnp.float32)], 0)              # [N+V]

    def to_real(ext_idx):
        """Map an extended node id to its real node (slots -> their node)."""
        if n_slots == 0:
            return ext_idx
        s = jnp.clip(ext_idx - n_nodes, 0, n_slots - 1)
        return jnp.where(ext_idx >= n_nodes, slot_node_c[s], ext_idx)

    # --- reservation fine-grained holds as EXTENDED pool rows -------------
    # Slot v's reserved GPU instances / NUMA zone capacity appear as row
    # N+v of the instance/zone pools: the existing per-instance and
    # per-zone prefix gates then hand consumers exactly the reserved
    # minors/zone with zero extra machinery (deviceshare/nodenumaresource
    # ReservationRestorePlugin; instance ids are the node's minors).
    if use_gpu and n_slots:
        devices_x = devices0.replace(
            gpu_total=jnp.concatenate(
                [devices0.gpu_total, devices0.gpu_total[slot_node_c]], 0),
            gpu_free=jnp.concatenate(
                [devices0.gpu_free, resv0.gpu_free], 0),
            gpu_valid=jnp.concatenate(
                [devices0.gpu_valid, resv0.gpu_valid], 0),
            gpu_numa=jnp.concatenate(
                [devices0.gpu_numa, devices0.gpu_numa[slot_node_c]], 0),
            gpu_pcie=jnp.concatenate(
                [devices0.gpu_pcie, devices0.gpu_pcie[slot_node_c]], 0))
    else:
        devices_x = devices0
    n_gpu_rows = devices_x.gpu_free.shape[0] if use_gpu else n_nodes
    if enable_numa:
        if n_slots:
            numa_cap_x = jnp.concatenate(
                [nodes0.numa_cap, resv0.numa_free], 0)       # [N+V, Z, 2]
            numa_valid_x = jnp.concatenate(
                [nodes0.numa_valid, resv0.numa_valid], 0)
            # slot rows engage only CPU-bind consumers (the reservation's
            # R-vector free covers plain consumers)
            numa_policy_x = jnp.concatenate(
                [numa_policy0,
                 jnp.zeros((n_slots,), numa_policy0.dtype)], 0)
            numa_used0_x = jnp.concatenate(
                [numa_used0, jnp.zeros_like(resv0.numa_free)], 0)
        else:
            numa_cap_x, numa_valid_x = nodes0.numa_cap, nodes0.numa_valid
            numa_policy_x, numa_used0_x = numa_policy0, numa_used0
        n_numa_rows = numa_cap_x.shape[0]
    else:
        numa_used0_x = numa_used0

    # PodTopologySpread (upstream hard constraints): [1, 1] matrices mean
    # no spread modeling and everything below compiles out. Within a
    # batch the gate is exact: the round-level feasibility and the
    # inner prefix cap both read counts derived from the carried
    # assignment. ACROSS batches the counts come from spread_count0,
    # which the builder recomputes from running + assumed pods — callers
    # chunking one logical workload must rebuild batches through the
    # builder (the informer/service flow) so each chunk sees the
    # previous chunks' assumes.
    def domain_machinery(dom_matrix, count0, member):
        """Shared (group x topology-domain) machinery for spread and
        inter-pod (anti-)affinity: the extended domain map (slot columns
        inherit their node's domain) and a counts closure over the
        carried assignment. `member[P, G]` marks which placed batch pods
        charge group g's domain count — membership is by selector match,
        so a matching pod placed in the same batch counts even when it
        carries no such constraint itself."""
        n_g, n_d = count0.shape
        if n_slots:
            dom_x = jnp.concatenate(
                [dom_matrix, dom_matrix[:, slot_node_c]], 1)  # [G, N+V]
        else:
            dom_x = dom_matrix

        def counts_flat(placed_now):
            # one charging implementation for in-batch and cross-batch
            # counts (charge_domain_counts); dom_x here is the
            # slot-extended map, so extended placements land on their
            # node's domain. Rows are sliced to the packing prefix —
            # members beyond it cannot exist under the contract (and
            # contribute nothing at full width), so the scatter shrinks
            # with the prefix, bit-identically.
            return charge_domain_counts(count0, dom_x, member[:pc],
                                        placed_now[:pc]).reshape(-1)

        return dom_x, counts_flat, n_g, n_d

    def _fit_rows(x, rows, fill):
        """Slice or pad the leading axis to `rows` (prefix interop:
        e.g. the numa block consumes per-instance GPU rows computed at
        the gpu prefix width)."""
        if x.shape[0] == rows:
            return x
        if x.shape[0] > rows:
            return x[:rows]
        pad = jnp.full((rows - x.shape[0],) + x.shape[1:], fill, x.dtype)
        return jnp.concatenate([x, pad], axis=0)

    _s_cls, _a_cls, _f_cls = dom_classes if dom_classes is not None \
        else (None, None, None)

    def _norm_classes(cls, n_g):
        """Singleton classes (the default) reduce the batched per-class
        matmul to the per-group matvec exactly."""
        if cls is None:
            return tuple((g,) for g in range(n_g))
        got = sorted(g for c in cls for g in c)
        if got != list(range(n_g)) or not all(len(c) for c in cls):
            raise ValueError(f"dom_classes must partition range({n_g}) "
                             f"into non-empty classes; got {cls}")
        return tuple(tuple(c) for c in cls)

    use_spread = pods.has_spread
    if use_spread:
        spread_domain_x, spread_counts_flat, n_sg, n_dom = \
            domain_machinery(pods.spread_domain, pods.spread_count0,
                             pods.spread_member)
        # multi-constraint gating rides the carrier MATRIX (zone +
        # hostname together is the upstream default profile): per-group
        # [Sg, N+V] admissibility maps combined by one bool matmul over
        # the CARRIED groups — the same shape as the anti gates
        spread_carrier_f = pods.spread_carrier.astype(jnp.float32)
        # SOFT groups (ScheduleAnyway) carry skew = inf from the
        # builder; they never filter — keyless nodes included
        spread_soft = ~jnp.isfinite(pods.spread_max_skew)      # [Sg]
        spread_classes = _norm_classes(_s_cls, n_sg)
    # inter-pod anti-affinity: a domain admits a gated pod only at count
    # 0; nodes LACKING the topology key pass (no topology pair can
    # exist there — upstream admits them).
    use_anti = pods.has_anti
    if use_anti:
        anti_domain_x, anti_counts_flat, n_ag, n_ad = \
            domain_machinery(pods.anti_domain, pods.anti_count0,
                             pods.anti_member)
        # direction (b): carrier occupancy per (group, domain)
        _, anti_carrier_flat, _, _ = \
            domain_machinery(pods.anti_domain, pods.anti_carrier_count0,
                             pods.anti_carrier)
        anti_member_f = pods.anti_member.astype(jnp.float32)  # [P, Ag]
        anti_carrier_f = pods.anti_carrier.astype(jnp.float32)
        anti_classes = _norm_classes(_a_cls, n_ag)
    # inter-pod affinity: a domain admits a gated pod only when it holds
    # a matching pod — except the bootstrap: when nothing matches
    # anywhere, any self-matching member may OPEN a domain, capped to
    # one opener per group per inner step so the group still converges
    # to co-location (upstream's self-affinity special case, without
    # pinning the bootstrap to one member that might be unschedulable).
    use_aff = pods.has_aff
    if use_aff:
        # multi-term gating rides the carrier matrix; the bootstrap is
        # per (pod, carried group): a self-matching member of an EMPTY
        # group may open any domain of that group
        aff_self = pods.aff_member & pods.aff_carrier       # bool[P, Fg]
        aff_domain_x, aff_counts_flat, n_fg, n_fd = \
            domain_machinery(pods.aff_domain, pods.aff_count0,
                             pods.aff_member)
        aff_classes = _norm_classes(_f_cls, n_fg)

    def round_body(carry, _):
        requested, quota_used, numa_used, gpu_free, aux_free, once_taken, \
            assigned_est, prod_assigned_est, gang_placed, placed, out_score, \
            out_zone, out_take, out_gpu_take, out_aux = carry
        active = pods.valid & (placed < 0) & gang_ok

        nodes = nodes0.replace(
            requested=requested[:n_nodes],
            assigned_estimated=assigned_est,
            prod_assigned_estimated=prod_assigned_est)

        # --- feasibility [P, N+V] (HOT LOOP #1) ---
        fit = jnp.all(dims(pods.requests)[:, None, :] + dims(requested)[None]
                      <= dims(ext_alloc)[None] + EPS, axis=-1)
        if enable_amplification and (fd is None or ci in fd):
            # CPU-bind pods must also fit their AMPLIFIED cpu request —
            # but only when the caller checks the CPU dim at all
            # (fit_dims excluding CPU must stay excluded)
            amp_cpu = pods.requests[:, ci][:, None] * jnp.where(
                pods.numa_single[:, None], amp_ext[None, :], 1.0)  # [P, N+V]
            fit &= amp_cpu + requested[None, :, ci] \
                <= ext_alloc[None, :, ci] + EPS
        feasible = fit & ext_static & active[:, None]
        if n_slots:
            # consumed AllocateOnce slots admit nobody (plugin.go:509-510)
            feasible &= ~jnp.concatenate(
                [jnp.zeros((n_nodes,), bool), is_once & once_taken])[None, :]

        # The three topology families gate only CONSTRAINED pods (rows
        # [0, pc) under the packing contract): their (pod x group)
        # matmuls run on prefix rows and the blocks merge into
        # `feasible` with one concatenation below.
        topo_blocks_pc = []
        if use_spread:
            # counts = initial matching pods + this batch's placements
            counts = spread_counts_flat(placed).reshape(n_sg, n_dom)
            min_c = jnp.min(jnp.where(pods.spread_dvalid, counts,
                                      jnp.inf), axis=1)             # [Sg]
            # no eligible domain -> minimum 0 (the sequential reference
            # in preemption.constraints_admit uses default=0, keeping a
            # hard group with unreachable domains RESTRICTIVE, not open)
            min_c = jnp.where(jnp.isfinite(min_c), min_c, 0.0)
            # per-(group, node) admissibility: placing one more pod in
            # the node's domain keeps the skew within the group's bound
            cnt_at = jnp.where(
                spread_domain_x >= 0,
                jnp.take_along_axis(counts,
                                    jnp.maximum(spread_domain_x, 0),
                                    axis=1), 0.0)        # [Sg, N+V]
            ok_map = (spread_soft[:, None]
                      | ((spread_domain_x >= 0)
                         & (cnt_at + 1.0 - min_c[:, None]
                            <= pods.spread_max_skew[:, None] + EPS)))
            # a pod is blocked where ANY carried group rejects the node
            topo_blocks_pc.append((spread_carrier_f[:pc]
                                   @ (~ok_map).astype(jnp.float32)) > 0.5)
            # preference (upstream spread Score): emptier domains rank
            # higher for BOTH hard and soft spread pods; normalize PER
            # GROUP (a crowded unrelated group must not flatten another
            # group's preference; the oracle mirrors) and SUM over the
            # pod's carried constraints (upstream sums per-constraint
            # scores)
            group_max = jnp.max(counts, axis=1)              # [Sg]
            penalty_map = jnp.where(
                spread_domain_x >= 0,
                cnt_at / jnp.maximum(group_max[:, None], 1.0)
                * MAX_NODE_SCORE, 0.0)                   # [Sg, N+V]
            spread_penalty_pc = spread_carrier_f[:pc] @ penalty_map
        if use_anti:
            counts_an = anti_counts_flat(placed).reshape(n_ag, n_ad)
            # (a) carriers avoid domains holding selector-matching pods
            # — a per-group [Ag, N+V] occupancy map and one bool matmul
            # over the CARRIED groups, so a pod carrying SEVERAL anti
            # terms is gated by each (multi-term pods; same shape as
            # direction (b)). Keyless nodes stay open per group: no
            # topology pair can exist there.
            occ_a = (jnp.where(
                anti_domain_x >= 0,
                jnp.take_along_axis(counts_an,
                                    jnp.maximum(anti_domain_x, 0),
                                    axis=1), 0.0) > 0.5)  # [Ag, N+V]
            topo_blocks_pc.append(
                (anti_carrier_f[:pc] @ occ_a.astype(jnp.float32)) > 0.5)
            # (b) selector-matching pods avoid CARRIER domains — one
            # bool matmul over groups covers pods matching several terms
            carr = anti_carrier_flat(placed).reshape(n_ag, n_ad)
            occ_b = (jnp.where(
                anti_domain_x >= 0,
                jnp.take_along_axis(carr, jnp.maximum(anti_domain_x, 0),
                                    axis=1), 0.0) > 0.5)  # [Ag, N+V]
            topo_blocks_pc.append(
                (anti_member_f[:pc] @ occ_b.astype(jnp.float32)) > 0.5)
        if use_aff:
            counts_af = aff_counts_flat(placed).reshape(n_fg, n_fd)
            total_af = jnp.sum(counts_af, axis=1)         # [Fg]
            cc_map = jnp.where(
                aff_domain_x >= 0,
                jnp.take_along_axis(counts_af,
                                    jnp.maximum(aff_domain_x, 0),
                                    axis=1), 0.0)         # [Fg, N+V]
            # bootstrap feasibility per (pod, carried group): ANY active
            # self-matching member of an empty group may open any of its
            # domains; the inner prefix caps openers to one per group
            # per step
            boot_pg = (active[:pc, None] & aff_self[:pc]
                       & (total_af < 0.5)[None, :])       # [pc, Fg]
            carried = pods.aff_carrier[:pc]
            # non-boot carried groups need a matching pod in the node's
            # domain; boot groups only need the domain to exist
            bad_nonboot = ((aff_domain_x < 0)
                           | (cc_map <= 0.5)).astype(jnp.float32)
            bad_boot = (aff_domain_x < 0).astype(jnp.float32)
            topo_blocks_pc.append((
                (carried & ~boot_pg).astype(jnp.float32) @ bad_nonboot
                + boot_pg.astype(jnp.float32) @ bad_boot) > 0.5)
        if topo_blocks_pc:
            blocked_pc = functools.reduce(jnp.logical_or, topo_blocks_pc)
            feasible = jnp.concatenate(
                [feasible[:pc] & ~blocked_pc, feasible[pc:]], axis=0)

        # quota admission (ElasticQuota PreFilter, plugin.go:211-257):
        # used + request <= runtime at every tree level
        quota_admit = jnp.ones((p,), bool)
        for d in range(quota_depth):
            anc = pod_anc[:, d]
            a = jnp.maximum(anc, 0)
            level_ok = jnp.all(dims(quota_used)[a] + dims(pods.requests)
                               <= dims(quotas0.runtime)[a] + EPS, axis=-1)
            quota_admit &= (anc < 0) | level_ok
        feasible &= quota_admit[:, None]

        # --- score [P, N] (HOT LOOP #2) + top-k select ---
        # The [P, N] matrices are computed ONCE per round; the commit then
        # runs k cheap [P]-sized inner steps in which every rejected pod
        # falls through to its next-best node. Within a round the LoadAware
        # inputs are frozen (the reference's NodeMetric does not change on
        # assume either); capacity and quota stay exact via prefix sums.
        scores = loadaware.score_matrix(nodes, pods, cfg, score_dims)
        if enable_numa:
            # framework sums plugin scores; NUMA preference only affects
            # NUMA-bound pods (numa_scores is 0 elsewhere)
            scores = scores + numa_scores
        if use_gpu:
            # device preference likewise only affects GPU-requesting pods
            scores = scores + dev_scores
        if taint_penalty is not None:
            # demote, never filter (upstream tainttoleration only scores):
            # the clamp keeps penalized-but-feasible nodes above the
            # infeasible sentinel (-1.0) and the inner 'trying' threshold
            scores = jnp.maximum(scores - taint_penalty, 0.0)
        if use_spread:
            # real-node columns only: slot columns carry their fixed
            # owner preference above any node score; non-carrier rows
            # (outside the packing prefix) have zero penalty by
            # construction
            scores = jnp.concatenate(
                [jnp.maximum(scores[:pc] - spread_penalty_pc[:, :n_nodes],
                             0.0), scores[pc:]], axis=0)
        if n_slots:
            # slot columns outscore any node sum: owners strictly prefer
            # their reservation (nominator preference); safe because slot-
            # eligible pods are never NUMA-bound nor device-requesting, so
            # their node scores top out at MAX_NODE_SCORE
            scores = jnp.concatenate(
                [scores, jnp.full((p, n_slots), 3.0 * MAX_NODE_SCORE + 1.0)],
                axis=1)
        if tie_break:
            # k8s selectHost picks uniformly among max-score nodes
            # (schedule_one.go reservoir sample); a deterministic per-
            # (pod, node) jitter < 0.5 reproduces that spread without
            # reordering distinct integer scores, and de-clusters the
            # batched argmax under contention.
            pi = jnp.arange(p, dtype=jnp.uint32)[:, None]
            ni = jnp.arange(n_ext, dtype=jnp.uint32)[None, :]
            h = (pi * jnp.uint32(2654435761) + ni * jnp.uint32(40503)) & 1023
            scores = scores + h.astype(jnp.float32) * (0.49 / 1024.0)
        with obs.phase(obs_phases.PHASE_TOPK):
            masked = jnp.where(feasible, scores, -1.0)
            k = min(k_choices, n_ext)
            if approx_topk:
                # TPU-optimized partial reduction (approx_max_k) — the
                # choice list is a heuristic preference order, so
                # bounded recall only means an occasional pod falls to
                # a later round.
                topk_val, topk_idx = jax.lax.approx_max_k(masked, k)
            else:
                topk_val, topk_idx = jax.lax.top_k(masked, k)
            topk_idx = topk_idx.astype(jnp.int32)

        def inner(inner_carry, _):
            requested, quota_used, numa_used, gpu_free, aux_free, \
                once_taken, placed, kptr, out_score, out_zone, out_take, \
                out_gpu_take, out_aux = inner_carry
            val = jnp.take_along_axis(topk_val, kptr[:, None], 1)[:, 0]
            choice = jnp.take_along_axis(topk_idx, kptr[:, None], 1)[:, 0]
            trying = active & (placed < 0) & (kptr < k) & (val > -0.5)
            if n_slots:
                # a once slot consumed by an earlier inner step admits nobody
                slot_of = jnp.clip(choice - n_nodes, 0, n_slots - 1)
                on_slot = choice >= n_nodes
                trying &= ~(on_slot & (is_once & once_taken)[slot_of])
            choice_eff = jnp.where(trying, choice, n_ext)

            # node/slot capacity prefix in priority order; a CPU-bind pod
            # charges its amplified cpu request on amplified nodes
            if enable_amplification:
                f_amp = jnp.where(
                    pods.numa_single,
                    amp_ext[jnp.clip(choice_eff, 0, n_ext - 1)], 1.0)  # [P]
                req_node = pods.requests.at[:, ci].mul(f_amp)
            else:
                req_node = pods.requests
            eff_req = jnp.where(trying[:, None], dims(req_node), 0.0)
            accept = trying & segment_prefix_ok(
                choice_eff, earlier, eff_req, dims(requested),
                dims(ext_alloc), n_ext)

            # In-step topology gates run on the packing prefix: every
            # member/carrier row sits below pc (contract), so the
            # same-domain [pc, pc] masks and matvecs cover all charges
            # and all gated pods; rows >= pc merge back accepted-as-is.
            if use_spread or use_anti or use_aff:
                earlier_pc = earlier[:pc, :pc]
                trying_pc = trying[:pc]
                choice_pc = jnp.clip(choice_eff[:pc], 0, n_ext - 1)
                accept_pc = accept[:pc]
            if use_spread:
                # spread within the step: per group, priority order caps
                # each domain at skew + round-start min (min rises
                # between rounds, releasing more; SOFT groups never
                # gate). Current counts come from the CARRIED
                # assignment, so allowance consumed in earlier inner
                # steps (kptr fall-throughs) is charged too. Groups
                # iterate per domain CLASS (identical domain rows share
                # one same-domain mask; the per-group matvecs batch into
                # one matmul), and the per-group columns let a pod
                # charge every group it MATCHES while being gated by
                # every group it CARRIES — multi-constraint pods.
                counts_s_now = spread_counts_flat(placed).reshape(
                    n_sg, n_dom)
                for cls in spread_classes:
                    ci_ = np.asarray(cls, dtype=np.int32)
                    dom_g = spread_domain_x[ci_[0], choice_pc]   # [pc]
                    has_dom = (dom_g >= 0)[:, None]
                    same_d = dom_g[:, None] == dom_g[None, :]
                    e_mask = (same_d & earlier_pc).astype(jnp.float32)
                    dom_c = jnp.maximum(dom_g, 0)
                    contrib = (trying_pc[:, None]
                               & pods.spread_member[:pc, ci_]
                               & has_dom).astype(jnp.float32)  # [pc, Gc]
                    gated = (trying_pc[:, None]
                             & pods.spread_carrier[:pc, ci_]
                             & has_dom & ~spread_soft[ci_][None, :])
                    occ = counts_s_now[ci_][:, dom_c].T \
                        + e_mask @ contrib                     # [pc, Gc]
                    limit_c = (pods.spread_max_skew[ci_]
                               + min_c[ci_])[None, :]
                    accept_pc &= jnp.all(
                        ~gated | (occ + 1.0 <= limit_c + EPS), axis=1)
            if use_anti:
                # anti-affinity within the step: every trying MEMBER
                # (selector-matching pod, gated or not) charges its
                # chosen domain; gated pods are rejected when any
                # earlier-ranked charge (or an initial count) occupies
                # it. Same class batching as spread; the per-group
                # columns let a pod contribute to several groups'
                # accounting while being gated by only its own.
                counts_an_now = anti_counts_flat(placed).reshape(
                    n_ag, n_ad)
                carr_now = anti_carrier_flat(placed).reshape(n_ag, n_ad)
                for cls in anti_classes:
                    ci_ = np.asarray(cls, dtype=np.int32)
                    dom_g = anti_domain_x[ci_[0], choice_pc]     # [pc]
                    has_dom = (dom_g >= 0)[:, None]
                    same_d = dom_g[:, None] == dom_g[None, :]
                    e_mask = (same_d & earlier_pc).astype(jnp.float32)
                    dom_c = jnp.maximum(dom_g, 0)
                    member_c = pods.anti_member[:pc, ci_]
                    carrier_c = pods.anti_carrier[:pc, ci_]
                    # occupancy of the pod's chosen domain BEFORE it:
                    # carried counts + earlier-ranked in-step charges
                    # (a) matching pods charge; carriers are gated
                    contrib_a = (trying_pc[:, None] & member_c
                                 & has_dom).astype(jnp.float32)
                    gated_a = trying_pc[:, None] & carrier_c & has_dom
                    occ_a = counts_an_now[ci_][:, dom_c].T \
                        + e_mask @ contrib_a
                    accept_pc &= jnp.all((occ_a < 0.5) | ~gated_a,
                                         axis=1)
                    # (b) carriers charge; matching pods are gated
                    contrib_b = (trying_pc[:, None] & carrier_c
                                 & has_dom).astype(jnp.float32)
                    gated_b = trying_pc[:, None] & member_c & has_dom
                    occ_b_g = carr_now[ci_][:, dom_c].T \
                        + e_mask @ contrib_b
                    accept_pc &= jnp.all((occ_b_g < 0.5) | ~gated_b,
                                         axis=1)
            if use_aff:
                # bootstrap cap: attempts into an EMPTY domain of an
                # empty group are limited to one per group per step —
                # per carried group, so a pod opening several groups is
                # capped in each (multi-term pods). The opener-ordering
                # mask is the plain earlier matrix (no same-domain
                # term), so all groups of a class batch into one matmul.
                counts_af_now = aff_counts_flat(placed).reshape(n_fg,
                                                                n_fd)
                total_now = jnp.sum(counts_af_now, axis=1)  # [Fg]
                e_full = earlier_pc.astype(jnp.float32)
                for cls in aff_classes:
                    ci_ = np.asarray(cls, dtype=np.int32)
                    dom_g = aff_domain_x[ci_[0], choice_pc]      # [pc]
                    cc_now = counts_af_now[ci_][
                        :, jnp.maximum(dom_g, 0)].T            # [pc, Gc]
                    # a carried pod trying an EMPTY domain of g is an
                    # opener attempt; it succeeds only when the whole
                    # group is still empty AND no earlier-ranked opener
                    # exists — once g is populated, empty-domain tries
                    # are rejected so the pod falls through (kptr) to
                    # the opened domain
                    boot_try = (trying_pc[:, None]
                                & pods.aff_carrier[:pc, ci_]
                                & (dom_g >= 0)[:, None]
                                & (cc_now < 0.5))              # [pc, Gc]
                    openers_before = e_full @ boot_try.astype(
                        jnp.float32)                           # [pc, Gc]
                    accept_pc &= jnp.all(
                        ~boot_try | (total_now[ci_][None, :]
                                     + openers_before < 0.5), axis=1)
            if use_spread or use_anti or use_aff:
                accept = jnp.concatenate([accept_pc, accept[pc:]], axis=0)

            # quota prefix per tree level, same trick
            for d in range(quota_depth):
                anc = jnp.where(accept, pod_anc[:, d], -1)
                anc_eff = jnp.where(anc >= 0, anc, n_quotas)
                acc_req = jnp.where(accept[:, None], dims(pods.requests), 0.0)
                accept &= segment_prefix_ok(
                    anc_eff, earlier, acc_req, dims(quota_used),
                    dims(quotas0.runtime), n_quotas)

            # All remaining gates only SHRINK accept; every scatter-commit
            # is deferred until accept is final, so a pod rejected by a
            # later gate (device, AllocateOnce) never leaves a stale zone/
            # instance charge behind.
            if use_gpu:
                # per-instance request at the chosen node, computed on
                # the device-prefix rows; the view slices ONLY the
                # fields per_instance_at reads (requests, gpu_ratio)
                pods_pg = pods.replace(requests=pods.requests[:pg],
                                       gpu_ratio=pods.gpu_ratio[:pg])
                g_count, g_per = deviceshare.per_instance_at(
                    devices_x, pods_pg, choice_eff[:pg])  # [pg], [pg, 3]
            if enable_numa:
                # --- topology manager (frameworkext/topologymanager) ---
                # Per-pod effective policy: a CPU-bind pod requires single-
                # numa-node everywhere (incl. on a reservation slot, whose
                # row holds the reserved zone); otherwise the chosen node's
                # policy applies (slot rows carry policy none). Under the
                # numa_prefix contract (no policy nodes; CPU-bind pods
                # packed below pn) only prefix rows can engage, so the
                # whole block runs on [pn] rows.
                choice_pn = choice_eff[:pn]
                nc_z = jnp.clip(choice_pn, 0, n_numa_rows - 1)
                eff_policy = jnp.where(
                    pods.numa_single[:pn],
                    topologymanager.POLICY_SINGLE_NUMA_NODE,
                    numa_policy_x[nc_z])
                eff_policy = jnp.where(trying[:pn], eff_policy, 0)
                engaged = eff_policy > topologymanager.POLICY_NONE
                free_z = jnp.maximum(
                    numa_cap_x[nc_z] - numa_used[nc_z], 0.0)
                validz = numa_valid_x[nc_z]                  # [pn, Z]
                req2_eff = req2_all[:pn] * engaged[:, None]
                provider_hints = [topologymanager.capacity_hints(
                    free_z, req2_eff, validz)]
                if use_gpu:
                    # gpu rows fitted to the numa width: rows in
                    # [pg, pn) carry no GPU request by contract, and
                    # zero-padding reproduces their per_instance_at
                    # output exactly
                    zcounts = deviceshare.gpu_zone_counts(
                        gpu_free, devices_x, choice_pn,
                        _fit_rows(g_per, pn, 0.0), n_zones)
                    provider_hints.append(topologymanager.count_hints(
                        zcounts, _fit_rows(g_count, pn, 0) * engaged))
                fit_m, pref_m = topologymanager.merge_hints(provider_hints)
                affinity, admit, _ = topologymanager.resolve(
                    fit_m, pref_m, eff_policy, free_z[..., 0], validz,
                    numa_strategy)
                numa_take, filled = topologymanager.greedy_take(
                    free_z, req2_eff, affinity, numa_strategy)
                acc_pn = accept[:pn] & admit & (~engaged | filled)
                # per-zone capacity prefix gates in priority order (the
                # same sequential-exactness trick as node capacity, one
                # [N+V, 2] segment space per zone; each zone observes
                # the previous zone's gate, like the full-width loop)
                for zz in range(n_zones):
                    znow = acc_pn & engaged
                    zseg = jnp.where(znow, choice_pn, n_numa_rows)
                    acc_pn &= segment_prefix_ok(
                        zseg, earlier[:pn, :pn],
                        numa_take[:, zz, :] * znow[:, None],
                        numa_used[:, zz, :], numa_cap_x[:, zz, :],
                        n_numa_rows)
                accept = jnp.concatenate([acc_pn, accept[pn:]], axis=0)

            if use_gpu:
                # --- GPU instance gates (deviceshare allocateDevices) ---
                # choice_eff indexes the EXTENDED instance pool: node rows
                # are the open per-instance free, slot rows the remaining
                # reserved holds — consumers take reserved minors here.
                # Under the gpu_prefix contract every device-requesting
                # pod sits below pg, so the whole block runs on [pg]
                # rows (non-device rows beyond are vacuously accepted).
                choice_pg = choice_eff[:pg]
                shared = g_count == 1
                multi = g_count > 1
                # with NUMA modeling off, the zone constraint is dropped
                # (not tightened against a sentinel mask); rows padded
                # past the numa width carry no policy (all-open mask)
                if enable_numa:
                    zone_mask_dev = _fit_rows(affinity, pg, True)
                    dev_engaged = _fit_rows(engaged, pg, False)
                else:
                    zone_mask_dev = jnp.ones((pg, 1), bool)
                    dev_engaged = jnp.zeros((pg,), bool)
                inst, inst_ok = deviceshare.choose_gpu_instance(
                    gpu_free, devices_x, choice_pg, g_per, shared,
                    zone_mask_dev, dev_engaged, device_strategy)
                acc_pg = accept[:pg]
                acc_pg &= ~shared | inst_ok
                gseg = jnp.where(acc_pg & shared,
                                 choice_pg * n_inst + inst,
                                 n_gpu_rows * n_inst)
                greq = g_per * (acc_pg & shared)[:, None]
                gpu_free_flat = gpu_free.reshape(-1, NUM_DEV_DIMS)
                acc_pg &= segment_prefix_ok(
                    gseg, earlier[:pg, :pg], greq,
                    jnp.zeros_like(gpu_free_flat),
                    gpu_free_flat, n_gpu_rows * n_inst)
                took_shared = acc_pg & shared
                # multi-GPU (whole instances): one winner per node per inner
                # step keeps lowest-index instance identity unambiguous;
                # contenders fall through to the next step/round. Instances
                # tentatively taken by this step's shared pods are excluded
                # (shared-before-multi intra-step order; exact order is
                # recovered at chunk size 1).
                shared_taken_now = jnp.zeros(
                    (n_gpu_rows * n_inst + 1,), bool).at[
                        jnp.where(took_shared, choice_pg * n_inst + inst,
                                  n_gpu_rows * n_inst)].set(True)[:-1]
                nc = jnp.clip(choice_pg, 0, n_gpu_rows - 1)
                take, enough = deviceshare.full_fit_instances(
                    gpu_free, devices_x, choice_pg, g_per, g_count,
                    zone_mask_dev, dev_engaged,
                    exclude=shared_taken_now.reshape(n_gpu_rows,
                                                     n_inst)[nc])
                same_node = choice_pg[:, None] == choice_pg[None, :]
                multi_cand = multi & acc_pg
                first_multi = ~jnp.any(earlier[:pg, :pg] & same_node
                                       & multi_cand[None, :], axis=-1)
                acc_pg = jnp.where(multi, acc_pg & first_multi & enough,
                                   acc_pg)
                accept = jnp.concatenate([acc_pg, accept[pg:]], axis=0)

            if use_aux:
                # --- aux (rdma/fpga) VF gates (default device handler) ---
                aux_free_flat = aux_free.reshape(-1, 1)
                aux_insts = []
                for t in range(NUM_AUX_TYPES):
                    a_req = pods.requests[:, deviceshare.AUX_KINDS[t]]
                    has = a_req > 0
                    a_inst, a_ok = deviceshare.choose_aux_instance(
                        aux_free, devices0, choice_eff, t, a_req,
                        device_strategy)
                    accept &= ~has | a_ok
                    base = (choice_eff * NUM_AUX_TYPES + t) * n_aux
                    aseg = jnp.where(accept & has, base + a_inst,
                                     n_nodes * NUM_AUX_TYPES * n_aux)
                    areq = (a_req * (accept & has))[:, None]
                    accept &= segment_prefix_ok(
                        aseg, earlier, areq, jnp.zeros_like(aux_free_flat),
                        aux_free_flat, n_nodes * NUM_AUX_TYPES * n_aux)
                    aux_insts.append(a_inst)

            if n_slots:
                # AllocateOnce: single consumer per slot — among this
                # step's accepted consumers, only the first in priority
                # order wins (plugin.go:509-510), then the slot closes.
                once_here = accept & on_slot & is_once[slot_of]
                same_slot = choice_eff[:, None] == choice_eff[None, :]
                first = ~jnp.any(earlier & same_slot & once_here[None, :],
                                 axis=-1)
                accept = jnp.where(once_here, accept & first, accept)
                once_win = accept & on_slot & is_once[slot_of]
                once_taken = once_taken.at[
                    jnp.where(once_win, slot_of, n_slots)].set(
                        True, mode="drop")

            # scatter-commit (assume; scheduler_adapter assume/forget) —
            # accept is final from here on; the NUMA/GPU commits read
            # and write only their prefix rows (engaged and device pods
            # live there by contract)
            if enable_numa:
                took_z = accept[:pn] & engaged
                numa_used = numa_used.at[
                    jnp.where(took_z, choice_pn, n_numa_rows)].add(
                        numa_take * took_z[:, None, None], mode="drop")
                out_take = jnp.concatenate(
                    [jnp.where(took_z[:, None, None], numa_take,
                               out_take[:pn]), out_take[pn:]], axis=0)
                # reported zone: the single zone for CPU-bind pods (feeds
                # the resource-status annotation)
                zone1 = jnp.argmax(affinity, axis=-1).astype(jnp.int32)
                out_zone = jnp.concatenate(
                    [jnp.where(took_z & pods.numa_single[:pn], zone1,
                               out_zone[:pn]), out_zone[pn:]], axis=0)
            if use_gpu:
                took_shared = accept[:pg] & shared
                gseg = jnp.where(took_shared, choice_pg * n_inst + inst,
                                 n_gpu_rows * n_inst)
                gpu_free = gpu_free.reshape(-1, NUM_DEV_DIMS).at[gseg].add(
                    -g_per * took_shared[:, None],
                    mode="drop").reshape(gpu_free.shape)
                took_multi = accept[:pg] & multi
                g_upd = (take[:, :, None] * g_per[:, None, :]
                         * took_multi[:, None, None])
                g_tgt = jnp.where(took_multi, choice_pg, n_gpu_rows)
                gpu_free = gpu_free.at[g_tgt].add(-g_upd, mode="drop")
                inst_onehot = (jnp.arange(n_inst, dtype=jnp.int32)[None, :]
                               == inst[:, None])
                out_gpu_take = jnp.concatenate(
                    [out_gpu_take[:pg]
                     | (inst_onehot & took_shared[:, None])
                     | (take & took_multi[:, None]),
                     out_gpu_take[pg:]], axis=0)
            if use_aux:
                aux_free_flat = aux_free.reshape(-1, 1)
                for t in range(NUM_AUX_TYPES):
                    a_req = pods.requests[:, deviceshare.AUX_KINDS[t]]
                    took_a = accept & (a_req > 0)
                    base = (choice_eff * NUM_AUX_TYPES + t) * n_aux
                    aseg = jnp.where(took_a, base + aux_insts[t],
                                     n_nodes * NUM_AUX_TYPES * n_aux)
                    aux_free_flat = aux_free_flat.at[aseg].add(
                        -(a_req * took_a)[:, None], mode="drop")
                    out_aux = out_aux.at[:, t].set(
                        jnp.where(took_a, aux_insts[t], out_aux[:, t]))
                aux_free = aux_free_flat.reshape(aux_free.shape)
            acc_req = pods.requests * accept[:, None]
            # node charge is amplified for CPU-bind pods; quota charges the
            # RAW request (quota admission is about the pod's own ask)
            acc_req_node = req_node * accept[:, None] \
                if enable_amplification else acc_req
            requested = requested.at[choice_eff].add(acc_req_node,
                                                     mode="drop")
            for d in range(quota_depth):
                anc = jnp.where(accept, pod_anc[:, d], -1)
                quota_used = quota_used.at[
                    jnp.where(anc >= 0, anc, n_quotas)].add(acc_req,
                                                            mode="drop")
            placed = jnp.where(accept, choice, placed)
            out_score = jnp.where(accept, val, out_score)
            # a rejected pod's chosen node just filled up: fall through
            kptr = jnp.where(trying & ~accept, kptr + 1, kptr)
            return (requested, quota_used, numa_used, gpu_free, aux_free,
                    once_taken, placed, kptr, out_score, out_zone, out_take,
                    out_gpu_take, out_aux), None

        (requested, quota_used, numa_used, gpu_free, aux_free, once_taken,
         placed, _, out_score, out_zone, out_take, out_gpu_take,
         out_aux), _ = \
            jax.lax.scan(
                inner,
                (requested, quota_used, numa_used, gpu_free, aux_free,
                 once_taken, placed, jnp.zeros((p,), jnp.int32), out_score,
                 out_zone, out_take, out_gpu_take, out_aux),
                None, length=k)

        # register newly placed pods' estimates for the next round's scores
        # (podAssignCache tracks reservation consumers on the REAL node too)
        new = (placed >= 0) & active
        tgt = jnp.where(new, to_real(placed), n_nodes)
        est = pods.estimated * new[:, None]
        assigned_est = assigned_est.at[tgt].add(est, mode="drop")
        is_prod = pods.priority_class == 4  # PriorityClass.PROD
        prod_assigned_est = prod_assigned_est.at[tgt].add(
            est * is_prod[:, None], mode="drop")
        gang_placed = gang_placed.at[jnp.where(new & (pods.gang_id >= 0),
                                               pods.gang_id, n_gangs)].add(
            1, mode="drop")
        return (requested, quota_used, numa_used, gpu_free, aux_free,
                once_taken, assigned_est, prod_assigned_est, gang_placed,
                placed, out_score, out_zone, out_take, out_gpu_take,
                out_aux), None

    n_zones0 = nodes0.numa_cap.shape[1]
    init = (
        jnp.concatenate([nodes0.requested,
                         jnp.zeros_like(slot_alloc0)], axis=0),
        quotas0.used,
        numa_used0_x,
        devices_x.gpu_free,
        devices0.aux_free,
        jnp.zeros((n_slots,), bool),
        nodes0.assigned_estimated,
        nodes0.prod_assigned_estimated,
        jnp.zeros((n_gangs,), jnp.int32),
        jnp.full((p,), -1, jnp.int32),
        jnp.full((p,), -1.0, jnp.float32),
        jnp.full((p,), -1, jnp.int32),
        jnp.zeros((p, n_zones0, 2), jnp.float32),
        jnp.zeros((p, n_inst), bool),
        jnp.full((p, NUM_AUX_TYPES), -1, jnp.int32))
    (_, _, _, _, _, _, _, _, gang_placed, placed, out_score, out_zone,
     out_take, out_gpu_take, out_aux), _ = \
        jax.lax.scan(round_body, init, None, length=num_rounds)

    # --- gang all-or-nothing rollback (Permit barrier, core.go:311-341) ---
    # A strict gang below quorum rolls back ONLY when no members remain
    # outstanding (still to be attempted in a later chunk of the scan or a
    # retry pass). With members outstanding, the placed ones stay ASSUMED —
    # the Permit wait of the reference: pods sit at the barrier until the
    # gang completes. Without this, a gang spanning bench CHUNK boundaries
    # could never form: each chunk would see a partial count and revoke
    # its own members. Reclaim of a waiting gang that never completes is
    # two-tier, as in the reference: `gang_failed` in the result flags
    # gangs PROVEN short this batch so the host can forget/un-assume their
    # earlier members immediately, and gangs whose failed members simply
    # never reappear (provable by no one device-side) fall to the Permit
    # timeout — GangDirectory.expire_waits + the store's forget path.
    gid = jnp.maximum(pods.gang_id, 0)
    attempted = jnp.zeros((n_gangs,), jnp.int32).at[
        jnp.where(pods.valid & (pods.gang_id >= 0), gid, n_gangs)].add(
        1, mode="drop")
    outstanding = jnp.maximum(
        gangs0.member_count - gangs0.assumed - attempted, 0)
    gang_total = gangs0.assumed + gang_placed
    # satisfied gangs are never group-rejected (core.go:286 PostFilter skips
    # the strict-mode gang rejection once the match policy latched)
    gang_fail = (gangs0.valid & gangs0.strict & ~gangs0.satisfied
                 & (gang_total < gangs0.min_member)
                 & (outstanding == 0))
    revoke = (placed >= 0) & (pods.gang_id >= 0) & gang_fail[gid]
    placed = jnp.where(revoke, -1, placed)

    # --- rebuild post-commit state from the final assignment --------------
    ok = placed >= 0
    res_slot = jnp.where(placed >= n_nodes, placed - n_nodes, -1)
    placed_real = jnp.where(ok, to_real(jnp.maximum(placed, 0)), -1)
    tgt = jnp.where(ok, placed_real, n_nodes)
    fin_req = pods.requests * ok[:, None]
    fin_est = pods.estimated * ok[:, None]
    is_prod = pods.priority_class == 4
    # reservation consumers don't grow node requested (covered capacity was
    # already charged by the reserve pod, plugin.go:521-613)
    node_req = fin_req * (res_slot < 0)[:, None]
    if enable_amplification:
        f_fin = jnp.where(
            ok & pods.numa_single,
            nodes0.cpu_amplification[jnp.clip(placed_real, 0,
                                              n_nodes - 1)], 1.0)
        node_req = node_req.at[:, ci].mul(f_fin)
    requested = nodes0.requested.at[tgt].add(node_req, mode="drop")
    assigned_est = nodes0.assigned_estimated.at[tgt].add(fin_est, mode="drop")
    prod_assigned_est = nodes0.prod_assigned_estimated.at[tgt].add(
        fin_est * is_prod[:, None], mode="drop")
    quota_used = quotas0.used
    for d in range(quota_depth):
        anc = jnp.where(ok, pod_anc[:, d], -1)
        quota_used = quota_used.at[jnp.where(anc >= 0, anc, n_quotas)].add(
            fin_req, mode="drop")
    gang_assumed = gangs0.assumed.at[jnp.where(ok & (pods.gang_id >= 0),
                                               pods.gang_id, n_gangs)].add(
        1, mode="drop")

    # NUMA zone usage from the surviving assignment (revoked gang members
    # give their takes back)
    numa_zone = jnp.where(ok & pods.numa_single, out_zone, -1)
    numa_free = nodes0.numa_free
    on_slot_fin = res_slot >= 0
    if enable_numa:
        # slot consumers drew from the reservation's hold, not the node's
        # open pool (the hold already left the node at snapshot build)
        node_numa_tgt = jnp.where(ok & ~on_slot_fin, tgt, n_nodes)
        numa_free = jnp.maximum(
            nodes0.numa_free.at[node_numa_tgt].add(
                -out_take * ok[:, None, None], mode="drop"), 0.0)

    # device pools from the surviving assignment (revoked gang members give
    # their instances back); per-instance requests are a pure function of
    # (pod, assigned node), so only the take masks carry through the scan
    new_devices = devices0
    gpu_take = out_gpu_take & ok[:, None]
    aux_inst = jnp.where(ok[:, None], out_aux, -1)
    per_f = None
    if use_gpu:
        _, per_f = deviceshare.per_instance_at(devices0, pods, placed_real)
        g_upd = gpu_take[:, :, None] * per_f[:, None, :]
        g_tgt = jnp.where(ok & ~on_slot_fin, placed_real, n_nodes)
        new_gpu_free = devices0.gpu_free.at[g_tgt].add(-g_upd, mode="drop")
        new_devices = new_devices.replace(
            gpu_free=jnp.maximum(new_gpu_free, 0.0))
    if use_aux:
        aux_flat = devices0.aux_free.reshape(-1, 1)
        for t in range(NUM_AUX_TYPES):
            a_req = pods.requests[:, deviceshare.AUX_KINDS[t]]
            took = ok & (a_req > 0) & (aux_inst[:, t] >= 0)
            base = (jnp.maximum(placed_real, 0) * NUM_AUX_TYPES + t) * n_aux
            aseg = jnp.where(took, base + aux_inst[:, t],
                             n_nodes * NUM_AUX_TYPES * n_aux)
            aux_flat = aux_flat.at[aseg].add(-(a_req * took)[:, None],
                                             mode="drop")
        new_devices = new_devices.replace(
            aux_free=jnp.maximum(
                aux_flat.reshape(devices0.aux_free.shape), 0.0))

    # slot rows outscore any node sum for strict preference; report those
    # capped at MaxNodeScore (node-placed NUMA/device pods legitimately
    # exceed 100 — plugin scores sum — and keep their real value)
    chosen_score = jnp.where(
        ok, jnp.where(res_slot >= 0,
                      jnp.minimum(out_score, MAX_NODE_SCORE), out_score),
        -1.0)
    new_snap = snap.replace(
        nodes=nodes0.replace(requested=requested,
                             assigned_estimated=assigned_est,
                             prod_assigned_estimated=prod_assigned_est,
                             numa_free=numa_free),
        quotas=quotas0.replace(used=quota_used),
        gangs=gangs0.replace(assumed=gang_assumed),
        reservations=rebuild_reservations(
            snap.reservations, pods, res_slot, ok,
            numa_take=out_take if enable_numa else None,
            gpu_take=gpu_take if use_gpu else None, gpu_per_inst=per_f),
        devices=new_devices,
        version=snap.version + 1,
    )
    return ScheduleResult(assignment=placed_real, chosen_score=chosen_score,
                          numa_zone=numa_zone,
                          numa_take=out_take * ok[:, None, None],
                          gpu_take=gpu_take,
                          aux_inst=aux_inst, res_slot=res_slot,
                          gang_failed=gang_fail,
                          snapshot=new_snap,
                          amplified=enable_amplification)


def overcommit_arrays_ok(requested, allocatable, num_nodes: int = None,
                         tol: float = 1.0) -> bool:
    """Array form of `overcommit_ok` for callers holding the capacity
    columns without the snapshot (the bench's non-serialized
    conformance arrays)."""
    req = np.asarray(requested)
    alloc = np.asarray(allocatable)
    if num_nodes is not None:
        if req[num_nodes:].any():
            return False  # a pad row was charged: provably a bug
        req, alloc = req[:num_nodes], alloc[:num_nodes]
    return bool((req <= alloc + tol).all())


def overcommit_ok(snap: ClusterSnapshot, num_nodes: int = None,
                  tol: float = 1.0) -> bool:
    """The no-overcommit invariant, host-side: requested <= allocatable
    + tol on the REAL node rows [0, num_nodes). THE one implementation
    the dryrun, the mesh smoke, and the conformance tests assert —
    `num_nodes` excludes the zero-capacity pad rows appended by
    parallel.pad_nodes_to_mesh (provably unschedulable, so they can
    never be charged; checking them would be vacuous, and a caller
    accidentally including a charged pad row must fail loudly here,
    not by tolerance). None checks every row (no padding)."""
    return overcommit_arrays_ok(snap.nodes.requested,
                                snap.nodes.allocatable, num_nodes, tol)


# the (count field, domain field, member field) triples of the
# cross-batch count rule — THE one place the pairing is encoded;
# bench.py, the dryrun, and the mesh tests all consume it
COUNT_FIELDS = ("spread_count0", "anti_count0", "anti_carrier_count0",
                "aff_count0")
_COUNT_RULE = (("spread_count0", "spread_domain", "spread_member"),
               ("anti_count0", "anti_domain", "anti_member"),
               ("anti_carrier_count0", "anti_domain", "anti_carrier"),
               ("aff_count0", "aff_domain", "aff_member"))


def charge_all_counts(counts: tuple, batch, assignment) -> tuple:
    """Thread a batch's placements into the carried (spread, anti,
    anti-carrier, affinity) counts — the cross-batch analogue of the
    builder recomputing count0 from running + assumed pods. `counts`
    is ordered per COUNT_FIELDS; callers chunking one logical workload
    replace the next chunk's count0 fields with the result."""
    return tuple(
        charge_domain_counts(c, getattr(batch, dom), getattr(batch, mem),
                             assignment)
        for c, (_, dom, mem) in zip(counts, _COUNT_RULE))


@shape_contract(
    count0="f32[SG,DM~pad:zero]", dom_matrix="i32[SG,N~pad:-1]",
    member="bool[P~pad:false,SG]",
    assignment="i32[P~pad:-1]", _returns="f32[SG,DM~pad:zero]",
    _pad="unplaced rows (assignment -1), non-members, and keyless "
         "nodes (domain -1) all charge the drop row; the SG symbol "
         "stands for any of the three constraint families")
def charge_domain_counts(count0: jnp.ndarray, dom_matrix: jnp.ndarray,
                         member: jnp.ndarray,
                         assignment: jnp.ndarray) -> jnp.ndarray:
    """Post-batch (group x domain) count update — the cross-batch
    analogue of the builder recomputing spread/anti/aff count0 from
    running + assumed pods. Callers chunking one logical workload
    through repeated schedule_batch calls thread the returned counts
    into the next chunk's count0 so each chunk sees the previous
    chunks' assumes (the same rule the domain_machinery docstring
    states for the informer flow).

    `assignment` must be NODE-level indices (< N; map reservation-slot
    placements to their node first). Same segment-sum as the in-batch
    counts closure: every placed member of group g charges g's domain
    for its node; non-members and unplaced rows drop out.
    """
    n_g, n_d = count0.shape
    pl = jnp.maximum(assignment, 0)
    dom_pg = dom_matrix.T[pl]                              # [P, G]
    ok = member & (assignment >= 0)[:, None]
    dom_pg = jnp.where(ok, dom_pg, -1)
    g_idx = jnp.arange(n_g, dtype=jnp.int32)[None, :]
    seg = jnp.where(dom_pg >= 0, g_idx * n_d + dom_pg,
                    n_g * n_d).reshape(-1)
    return count0.reshape(-1).at[seg].add(
        1.0, mode="drop").reshape(n_g, n_d)


# --- device-resident straggler tail -------------------------------------
# After a chunked sweep some pods remain unplaced (conflict losers,
# constraint-tight rows). The tail packs them into fixed-width retry
# batches and re-schedules them with a heavier program (more rounds /
# fall-through choices). `tail_pass` is ONE such pass; the host may
# orchestrate passes itself (one straggler-count readback per adaptive
# decision — the conformance oracle, bench tail_mode=host), or run
# `tail_compaction_loop`, which drives the same pass inside a
# lax.while_loop so the whole adaptive tail — gather, compact, retry,
# repeat — stays on device and the host reads back ONE stats vector at
# the end regardless of straggler count.


@shape_contract(
    pods="PodBatch", assign="i32[P~pad:-1]", tried="bool[P~pad:false]",
    _returns=("i32[TC]", "bool[TC]"),
    _static={"tail_chunk": "TC"},
    _pad="requires tail_chunk <= P (the window gathers batch rows); "
         "rows of idx beyond the straggler pool are padding; attempt "
         "marks the true leftovers this pass may retry")
def tail_select(pods: PodBatch, assign: jnp.ndarray, tried: jnp.ndarray,
                tail_chunk: int, topo_prefix: int = None,
                topo_mask: jnp.ndarray = None):
    """Pick up to `tail_chunk` stragglers for one retry pass.

    Returns (idx i32[tail_chunk], attempt bool[tail_chunk]): the batch
    rows to gather and which of them are true leftovers this pass may
    retry (the rest are padding — marked invalid by the caller).

    Selection prefers NEVER-RETRIED leftovers over already-retried
    ones, so retry capacity is genuinely exhausted: without the `tried`
    mask, a pass that placed nothing would re-select the same window
    and silently starve the rest.

    Full-gate (`topo_prefix` set, `topo_mask` bool[P] in the batch's
    packed order): at most topo_prefix constrained stragglers (untried
    first) sort to the FRONT of the window — inside the scheduler's
    packing prefix — and the remaining slots go to unconstrained
    stragglers. Constrained overflow is excluded from the pass AND left
    unmarked in `tried`, so it stays in the never-retried pool and an
    adaptive caller keeps running until it drains; the in-prefix mask
    below is the safety net for the degenerate few-stragglers case.
    """
    with obs.phase(obs_phases.PHASE_TAIL_SELECT):
        return _tail_select_body(pods, assign, tried, tail_chunk,
                                 topo_prefix, topo_mask)


def _tail_select_body(pods, assign, tried, tail_chunk, topo_prefix,
                      topo_mask):
    bad = pods.valid & (assign < 0)
    if topo_prefix is None:
        key = jnp.where(bad & ~tried, 0, jnp.where(bad, 1, 2))
    else:
        # budgeted constrained selection: rank constrained stragglers
        # untried-first and admit only the first topo_prefix of them to
        # this pass — the REST of the window goes to unconstrained
        # stragglers (untried first), so constrained overflow occupies
        # no dead slots and can never starve unconstrained retries
        cb = bad & topo_mask
        ckey = jnp.where(cb & ~tried, 0, jnp.where(cb, 1, 2))
        adm = cb & (stable_rank(ckey) < topo_prefix)
        # untried pods of EITHER class outrank every tried pod
        # (admitted-constrained tried included), so no untried straggler
        # can be starved by retry loops of failing pods; admitted-tried
        # rows displaced beyond the prefix are caught by the in_prefix
        # mask
        key = jnp.where(
            adm & ~tried, 0,
            jnp.where(bad & ~topo_mask & ~tried, 1,
                      jnp.where(adm, 2,
                                jnp.where(bad & ~topo_mask, 3,
                                          jnp.where(bad, 4, 5)))))
    order = jnp.argsort(key, stable=True)
    idx = order[:tail_chunk]
    attempt = bad[idx]
    if topo_prefix is not None:
        in_prefix = jnp.arange(tail_chunk) < topo_prefix
        attempt &= ~topo_mask[idx] | in_prefix
    return idx, attempt


@shape_contract(
    snap="ClusterSnapshot",
    counts=("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
            "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
    assign="i32[P~pad:-1]", tried="bool[P~pad:false]", pods="PodBatch",
    cfg="LoadAwareConfig",
    _returns=("ClusterSnapshot",
              ("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
               "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
              "i32[P~pad:-1]", "bool[P~pad:false]"),
    _static={"tail_chunk": "TC"},
    _callable={"step_fn": "koordinator_tpu.scheduler.core.schedule_batch"},
    _pad="counts ride COUNT_FIELDS order; a pass with nothing left "
         "gathers an all-invalid retry batch and no-ops the snapshot")
def tail_pass(step_fn, snap: ClusterSnapshot, counts: tuple,
              assign: jnp.ndarray, tried: jnp.ndarray, pods: PodBatch,
              cfg, *, tail_chunk: int, charge_counts: bool = True,
              topo_prefix: int = None, topo_mask: jnp.ndarray = None):
    """One retry pass: gather the selected stragglers into a compact
    [tail_chunk] batch, re-schedule via `step_fn(snap, retry, cfg)`,
    and scatter placements back. Returns (snap, counts, assign, tried).

    The gathered retry batch marks only true leftovers valid, so a pass
    with nothing left is a no-op on the snapshot. `counts` is the
    carried (group x domain) topology-count tuple (COUNT_FIELDS order);
    `charge_counts=False` skips the cross-batch charge for workloads
    without topology terms (the slim bench path).
    """
    idx, attempt = tail_select(pods, assign, tried, tail_chunk,
                               topo_prefix, topo_mask)
    with obs.phase(obs_phases.PHASE_TAIL_PASS):
        retry = pods.replace(
            **{f: getattr(pods, f)[idx]
               for f in PER_POD_FIELDS if f != "valid"},
            valid=attempt)
        retry = retry.replace(**dict(zip(COUNT_FIELDS, counts)))
        tried = tried.at[idx].set(tried[idx] | attempt)
        res = step_fn(snap, retry, cfg)
        if charge_counts:
            counts = charge_all_counts(counts, retry, res.assignment)
        got = attempt & (res.assignment >= 0)
        assign = assign.at[idx].set(
            jnp.where(got, res.assignment, assign[idx]))
        return res.snapshot, counts, assign, tried


@shape_contract(
    snap="ClusterSnapshot",
    counts=("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
            "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
    assign="i32[P~pad:-1]", pods="PodBatch", cfg="LoadAwareConfig",
    _returns=("ClusterSnapshot",
              ("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
               "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
              "i32[P~pad:-1]", "i32[4]"),
    _static={"tail_chunk": "TC", "min_passes": 1, "max_passes": 2},
    _callable={"step_fn": "koordinator_tpu.scheduler.core.schedule_batch"},
    _pad="stats = [after_sweep, final, never_retried, passes]; only "
         "the max_passes cap can leave never_retried > 0")
def tail_compaction_loop(step_fn, snap: ClusterSnapshot, counts: tuple,
                         assign: jnp.ndarray, pods: PodBatch, cfg, *,
                         tail_chunk: int, min_passes: int, max_passes: int,
                         charge_counts: bool = True,
                         topo_prefix: int = None,
                         topo_mask: jnp.ndarray = None):
    """The device-resident adaptive tail: run `tail_pass` inside a
    lax.while_loop until the stragglers drain or the retry budget is
    spent, entirely on device.

    Returns (snap, counts, assign, stats) with stats i32[4] =
    [stragglers_after_sweep, stragglers_final, never_retried, passes] —
    a host that wants the numbers pays exactly ONE readback, after the
    loop, instead of one blocking straggler-count transfer per adaptive
    decision (each cost a full tunnel round-trip, ~100 ms; the 10-pass
    full-gate tail paid up to 10 of them).

    Retry-budget semantics (mirrors the host-driven oracle pass for
    pass, so placements are bit-identical — tests/test_cascade.py):
    - `min(min_passes, max_passes)` passes always run, even with zero
      stragglers (the warm-path contract callers rely on);
    - further passes run while stragglers remain AND (the count
      improved over the previous pass OR never-retried stragglers
      remain — a pass that placed nothing must not strand disjoint
      windows that were never tried), up to `max_passes`;
    - only the max_passes cap can leave never_retried > 0 (the caller
      should surface that loudly — bench does).
    """
    p = pods.valid.shape[0]
    min_eff = min(int(min_passes), int(max_passes))

    def left_count(assign):
        return jnp.sum(pods.valid & (assign < 0)).astype(jnp.int32)

    left0 = left_count(assign)

    def cond(carry):
        _, _, _, _, passes, left, improved, never_retried = carry
        forced = passes < min_eff
        adaptive = ((passes < max_passes) & (left > 0)
                    & (improved | (never_retried > 0)))
        return forced | adaptive

    def body(carry):
        snap, counts, assign, tried, passes, left, _, _ = carry
        snap, counts, assign, tried = tail_pass(
            step_fn, snap, counts, assign, tried, pods, cfg,
            tail_chunk=tail_chunk, charge_counts=charge_counts,
            topo_prefix=topo_prefix, topo_mask=topo_mask)
        bad = pods.valid & (assign < 0)
        new_left = jnp.sum(bad).astype(jnp.int32)
        never_retried = jnp.sum(bad & ~tried).astype(jnp.int32)
        return (snap, counts, assign, tried, passes + jnp.int32(1),
                new_left, new_left < left, never_retried)

    init = (snap, counts, assign, jnp.zeros((p,), bool), jnp.int32(0),
            left0, jnp.asarray(False), left0)
    with obs.phase(obs_phases.PHASE_TAIL_LOOP):
        (snap, counts, assign, _, passes, left, _, never_retried) = \
            jax.lax.while_loop(cond, body, init)
    stats = jnp.stack([left0, left, never_retried, passes])
    return snap, counts, assign, stats
