"""Chunk-granular commit journal: the crash-durability seam of the
scheduling service (docs/DESIGN.md "Crash recovery & mesh elasticity").

Koordinator survives scheduler restarts because every decision lives in
the API server; our device-resident mirror loses the in-flight batch on
a process crash. The journal closes that gap with the classic
write-ahead discipline, chunk-granular so recovery never re-opens more
work than the crash actually interrupted:

- after each chunk's device program completes — and BEFORE its result
  can be published anywhere — the service appends one checksummed
  record: (epoch, chunk, n_chunks, store base version, delta watermark,
  batch digest, the chunk's assignment row block). Append-before-
  publish means the journal is always a SUPERSET of what any external
  observer saw, so replay can only re-derive, never invent.
- replay is idempotent keyed by (epoch, chunk): a record that already
  exists with identical payload is a no-op; one that exists with a
  DIFFERENT payload is a conflict and fails loudly (recovery diverged
  from the original run — continuing would corrupt placements).
- the tail is torn-write tolerant: a SIGKILL mid-append leaves a
  truncated record, which load discards with a typed reason
  (`JournalTail`) and the next append truncates away. A checksum
  mismatch anywhere BEFORE the tail is real corruption and raises.

The file format is pure struct + raw int32 bytes — no pickle — so a
journal written by one process version replays in any other.

File I/O here runs under `SchedulerService.commit_lock` by design
(append-before-publish must be inside the commit critical section);
this module is the ONE sanctioned seam for that — koordlint LK005
flags commit-lock file I/O everywhere else. Appends are bounded:
one header + one int32 row block per chunk, one flush+fsync.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import struct
import zlib
from typing import Callable, Dict, List, Optional, Set

import numpy as np

from koordinator_tpu.snapshot.schema import STRUCT_SPECS
from koordinator_tpu.utils.sync import guarded_by

# record framing: MAGIC, payload length, crc32(payload)
_MAGIC = 0x4B4A4C31  # "KJL1"
_HEADER = struct.Struct("<III")
# payload head: epoch, chunk, n_chunks, chunk_size, base_version,
# delta_watermark, batch_digest — assignment int32 bytes follow
_PAYLOAD_HEAD = struct.Struct("<IIIIQQI")

# the named crash points the kill-injected soak drives
# (testing/faults.sigkill_at + tools/crash_smoke.py); the journal owns
# the three append-seam points, SnapshotStore.checkpoint owns the
# fourth ("mid_checkpoint")
POINT_PRE_APPEND = "post_dispatch_pre_append"
POINT_MID_APPEND = "mid_append_torn"
POINT_POST_APPEND = "post_append_pre_publish"


class JournalTail(enum.Enum):
    """What the load pass found at the end of the file. A torn tail is
    the EXPECTED shape of a crash mid-append — discarded, never fatal."""

    CLEAN = "clean"
    TORN_HEADER = "torn_header"    # fewer bytes than one record header
    TORN_PAYLOAD = "torn_payload"  # header promises more bytes than exist


class JournalCorruption(RuntimeError):
    """A record BEFORE the tail failed its checksum or framing — not a
    torn write (those only truncate the tail) but real corruption; the
    journal cannot be trusted and recovery must fail loudly."""

    def __init__(self, path: str, offset: int, why: str):
        super().__init__(f"journal {path!r} corrupt at byte {offset}: {why}")
        self.offset = offset


class JournalConflict(RuntimeError):
    """A (epoch, chunk) commit disagrees with the already-journaled
    record — replay diverged from the original run (different snapshot
    rehydration, different batch). Terminal by construction: retrying
    re-derives the same divergence."""


@dataclasses.dataclass(frozen=True)
class JournalRecord:
    """One committed chunk. `base_version` is the store version the
    whole batch read its snapshot at (shared by every chunk of an
    epoch); `delta_watermark` the store's applied_delta_version at
    append time; `batch_digest` pins the resubmitted batch on resume."""

    epoch: int
    chunk: int
    n_chunks: int
    base_version: int
    delta_watermark: int
    batch_digest: int
    assignment: np.ndarray  # i32[chunk_size]

    def same_payload(self, other: "JournalRecord") -> bool:
        return (self.n_chunks == other.n_chunks
                and self.base_version == other.base_version
                and self.batch_digest == other.batch_digest
                and np.array_equal(self.assignment, other.assignment))

    def encode(self) -> bytes:
        a = np.ascontiguousarray(self.assignment, np.int32)
        return _PAYLOAD_HEAD.pack(
            self.epoch, self.chunk, self.n_chunks, a.size,
            self.base_version, self.delta_watermark,
            self.batch_digest) + a.tobytes()

    @classmethod
    def decode(cls, payload: bytes) -> "JournalRecord":
        (epoch, chunk, n_chunks, size, base, watermark,
         digest) = _PAYLOAD_HEAD.unpack_from(payload)
        body = payload[_PAYLOAD_HEAD.size:]
        if len(body) != 4 * size:
            raise ValueError(f"payload claims {size} assignment rows, "
                             f"carries {len(body)} bytes")
        return cls(epoch=epoch, chunk=chunk, n_chunks=n_chunks,
                   base_version=base, delta_watermark=watermark,
                   batch_digest=digest,
                   assignment=np.frombuffer(body, np.int32).copy())


def batch_digest(pods) -> int:
    """Content digest of the batch a journaled epoch scheduled — the
    resume guard: a resubmitted batch whose rows differ must not be
    silently completed against another batch's committed chunks.
    Covers EVERY registered array column of the PodBatch (requests,
    gang/quota/selector/toleration ids, domain matrices, counts, ...),
    per the koordshape field-spec table, so no schedulable input can
    differ without changing the digest."""
    d = 0
    for fname in sorted(STRUCT_SPECS["PodBatch"]):
        spec = STRUCT_SPECS["PodBatch"][fname]
        if not (isinstance(spec, str) and "[" in spec):
            continue  # symbolic-int property (num_pods), not a column
        a = np.ascontiguousarray(np.asarray(getattr(pods, fname)))
        d = zlib.crc32(fname.encode() + repr(a.shape).encode(), d)
        d = zlib.crc32(a.tobytes(), d)
    return d & 0xFFFFFFFF


@guarded_by(
    # the journal deliberately owns NO lock: every mutation happens
    # inside the owning service's commit critical section (append-
    # before-publish), so the commit lock IS the journal's lock
    records="external:SchedulerService._commit_lock",
    abandoned="external:SchedulerService._commit_lock",
    tail_reason="external:SchedulerService._commit_lock",
    appended_records="external:SchedulerService._commit_lock",
    appended_bytes="external:SchedulerService._commit_lock",
    _good_end="external:SchedulerService._commit_lock",
    path="publish-once",
    crash_hook="publish-once",
)
class CommitJournal:
    """Append-only, checksummed, torn-tail-tolerant chunk commit log.

    `crash_hook` (testing seam) is called with the named crash point
    at the three append stages; `faults.sigkill_at` turns one of them
    into a real SIGKILL for the kill-injected soak.
    """

    def __init__(self, path: str,
                 crash_hook: Optional[Callable[[str], None]] = None):
        self.path = str(path)
        self.crash_hook = crash_hook
        # epoch -> {chunk -> record}
        self.records: Dict[int, Dict[int, JournalRecord]] = {}
        # epochs closed by a durable tombstone (abandon()): their
        # records never replay, and next_epoch moves past them
        self.abandoned: Set[int] = set()
        self.tail_reason = JournalTail.CLEAN
        self.appended_records = 0  # this process's appends
        self.appended_bytes = 0
        self._good_end = 0
        self._load()

    # --- load / scan -------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            if len(data) - off < _HEADER.size:
                self.tail_reason = JournalTail.TORN_HEADER
                break
            magic, length, crc = _HEADER.unpack_from(data, off)
            if magic != _MAGIC:
                raise JournalCorruption(self.path, off, "bad record magic")
            start = off + _HEADER.size
            if len(data) - start < length:
                self.tail_reason = JournalTail.TORN_PAYLOAD
                break
            payload = data[start:start + length]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                # a full-length record with a bad checksum is NOT a torn
                # tail (truncation only shortens): fail loudly
                raise JournalCorruption(self.path, off,
                                        "payload checksum mismatch")
            try:
                rec = JournalRecord.decode(payload)
            except ValueError as exc:
                raise JournalCorruption(self.path, off, str(exc)) from exc
            self._index(rec, loading=True)
            off = start + length
            self._good_end = off

    def _index(self, rec: JournalRecord, loading: bool) -> bool:
        """-> True if the record is new; False for an identical
        duplicate (idempotent no-op); raises on a conflicting one.
        An n_chunks of 0 is the epoch TOMBSTONE (abandon)."""
        if rec.n_chunks == 0:
            self.abandoned.add(rec.epoch)
            # keep any pre-tombstone chunk rows indexed (so next_epoch
            # still sees the epoch) but never replay them (records_for)
            self.records.setdefault(rec.epoch, {})
            return True
        self._check_conflict(rec, loading)
        by_chunk = self.records.setdefault(rec.epoch, {})
        prior = by_chunk.get(rec.chunk)
        if prior is not None:
            return False  # identical duplicate (_check_conflict ruled
            #               out a divergent one)
        by_chunk[rec.chunk] = rec
        return True

    def _check_conflict(self, rec: JournalRecord, loading: bool) -> bool:
        """Validate a non-tombstone record against the index WITHOUT
        touching it (runs BEFORE any durable write on the append path,
        so a divergent record never half-lands on disk). -> True when
        the record already exists identically."""
        if rec.epoch in self.abandoned:
            raise JournalConflict(
                f"epoch {rec.epoch} was abandoned; appending to it "
                f"would resurrect placements the tombstone closed")
        by_chunk = self.records.get(rec.epoch, {})
        prior = by_chunk.get(rec.chunk)
        if prior is not None:
            if prior.same_payload(rec):
                return True
            raise JournalConflict(
                f"(epoch {rec.epoch}, chunk {rec.chunk}) re-committed "
                f"with a different payload"
                + (" while loading" if loading else
                   " — recovery diverged from the journaled run"))
        if by_chunk and rec.n_chunks != \
                next(iter(by_chunk.values())).n_chunks:
            raise JournalConflict(
                f"epoch {rec.epoch} records disagree on n_chunks")
        return False

    # --- append ------------------------------------------------------------

    def _hook(self, point: str) -> None:
        if self.crash_hook is not None:
            self.crash_hook(point)

    def append(self, rec: JournalRecord) -> int:
        """Durably commit one chunk. Returns the bytes written, or 0
        when the record already exists identically (idempotent replay);
        raises JournalConflict on a divergent duplicate. ALL conflict
        checks (divergent payload, n_chunks drift, abandoned epoch) run
        BEFORE touching the file, so a conflicting record never lands
        durably only to make the journal unloadable."""
        self._hook(POINT_PRE_APPEND)
        if rec.n_chunks != 0 and self._check_conflict(rec, loading=False):
            self._hook(POINT_POST_APPEND)
            return 0  # identical duplicate: idempotent no-op
        payload = rec.encode()
        buf = _HEADER.pack(_MAGIC, len(payload),
                           zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with open(self.path, "r+b" if os.path.exists(self.path)
                  else "w+b") as f:
            # a torn tail from a previous crash is discarded here — the
            # new record starts at the last good byte
            f.truncate(self._good_end)
            f.seek(self._good_end)
            half = len(buf) // 2
            f.write(buf[:half])
            f.flush()
            self._hook(POINT_MID_APPEND)  # SIGKILL here = torn write
            f.write(buf[half:])
            f.flush()
            os.fsync(f.fileno())
        self.tail_reason = JournalTail.CLEAN
        self._good_end += len(buf)
        self._index(rec, loading=False)
        self.appended_records += 1
        self.appended_bytes += len(buf)
        self._hook(POINT_POST_APPEND)
        return len(buf)

    def abandon(self, epoch: int) -> int:
        """Durably close an epoch with a tombstone: its journaled
        chunks never replay again and `next_epoch` moves past it.
        SAFE only because an incomplete epoch has by construction
        published NOTHING (the store publish is what seals an epoch),
        so dropping its chunks loses no externally-visible placement —
        the unwedge path for an interrupted batch that will never be
        resubmitted (SchedulerService.abandon_interrupted_epoch) and
        for a retry whose base snapshot moved under it. Idempotent."""
        if epoch in self.abandoned:
            return 0
        return self.append(JournalRecord(
            epoch=epoch, chunk=0, n_chunks=0, base_version=0,
            delta_watermark=0, batch_digest=0,
            assignment=np.zeros((0,), np.int32)))

    def prune(self, min_base_version: int) -> int:
        """Checkpoint-anchored truncation: drop epochs that can never
        replay again — complete (or abandoned) epochs whose base
        version is BELOW the last durable checkpoint's store version
        (recovery only replays `base_version >= store.version`, and a
        restored store is never older than its checkpoint). The most
        recent epoch is always kept so `next_epoch` stays monotonic
        across restarts. Without this a resident service accretes
        every assignment ever committed, in RAM and on disk, and
        reload cost grows with lifetime throughput. Atomic (tmp +
        os.replace); returns the number of epochs dropped. Call it
        serialized with appends (the service prunes under its commit
        lock, right after a successful checkpoint)."""
        if not self.records:
            return 0
        last = max(self.records)
        dead = [
            e for e in self.records
            if e != last and self.epoch_complete(e)
            and (e in self.abandoned
                 or self.base_version_of(e) < min_base_version)]
        if not dead:
            return 0
        keep: List[JournalRecord] = []
        for e in sorted(self.records):
            if e in dead:
                continue
            if e in self.abandoned:
                # the tombstone alone: the epoch's chunk rows are
                # masked forever, and a record written AFTER its
                # tombstone would refuse to load
                keep.append(JournalRecord(
                    epoch=e, chunk=0, n_chunks=0, base_version=0,
                    delta_watermark=0, batch_digest=0,
                    assignment=np.zeros((0,), np.int32)))
                self.records[e] = {}
                continue
            keep.extend(self.records[e][c]
                        for c in sorted(self.records[e]))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for r in keep:
                payload = r.encode()
                f.write(_HEADER.pack(_MAGIC, len(payload),
                                     zlib.crc32(payload) & 0xFFFFFFFF))
                f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        for e in dead:
            self.records.pop(e, None)
            self.abandoned.discard(e)
        self._good_end = os.path.getsize(self.path)
        self.tail_reason = JournalTail.CLEAN
        return len(dead)

    # --- queries -----------------------------------------------------------

    def records_for(self, epoch: int) -> Dict[int, JournalRecord]:
        if epoch in self.abandoned:
            return {}
        return dict(self.records.get(epoch, {}))

    def epochs(self) -> List[int]:
        return sorted(e for e in self.records if e not in self.abandoned)

    def n_chunks_of(self, epoch: int) -> Optional[int]:
        by_chunk = self.records_for(epoch)
        if not by_chunk:
            return None
        return next(iter(by_chunk.values())).n_chunks

    def base_version_of(self, epoch: int) -> Optional[int]:
        by_chunk = self.records_for(epoch)
        if not by_chunk:
            return None
        return next(iter(by_chunk.values())).base_version

    def epoch_complete(self, epoch: int) -> bool:
        """A tombstoned epoch counts as CLOSED (complete for epoch
        accounting, empty for replay)."""
        if epoch in self.abandoned:
            return True
        by_chunk = self.records.get(epoch)
        if not by_chunk:
            return False
        n = next(iter(by_chunk.values())).n_chunks
        return set(by_chunk) == set(range(n))

    def next_epoch(self) -> int:
        """The epoch the service should run next: a fresh journal
        starts at 1; a journal whose last epoch is incomplete RESUMES
        that epoch (its committed chunks replay idempotently); a
        tombstoned last epoch is closed and skipped."""
        if not self.records:
            return 1
        last = max(self.records)
        return last + 1 if self.epoch_complete(last) else last
