"""Topology-manager hint merge: the four NUMA policies as batched mask
reductions.

Behavior parity with pkg/scheduler/frameworkext/topologymanager/ (SURVEY.md
2.1): per-node policy (none / best-effort / restricted / single-numa-node,
apis/extension/numa_aware.go:138-145), per-plugin hint providers (CPU+memory
from NodeNUMAResource, instance zones from DeviceShare), hints merged into
one NUMA affinity per pod, admission per policy (policy_best_effort.go,
policy_restricted.go, policy_single_numa_node.go, policy_none.go).

TPU design — no recursion, no bitmask objects: every affinity candidate is
one row of a fixed [M, Z] mask table (M = 2^Z, Z <= MAX_ZONES small). A
provider's hint list becomes two boolean [P, M] tensors:

  fit[p, m]  — the request fits in the combined free of mask m's zones
  pref[p, m] — m is MINIMAL for this provider (kubelet "preferred" =
               narrowest possible; policy.go mergePermutation keeps
               preferred only when every provider hint is preferred)

The reference's recursive permutation walk (policy.go
iterateAllProviderTopologyHints) reduces to per-mask ANDs because provider
hint sets here are monotone in the zone set (more zones never lose
capacity): a merged candidate c is achievable iff every provider fits c
directly, and it is preferred iff every provider is minimal at c. One
documented deviation: permutations of *differing* multi-zone preferred
hints whose bitwise AND is a strict subset of each (kubelet would emit the
intersection as "preferred" even though no provider can actually allocate
inside it) are not generated — that kubelet corner admits pods the zones
cannot hold, which a capacity-exact scheduler must not do.

Best-hint selection (policy.go mergeFilteredHints ordering): preferred
first, then narrowest (popcount), then hint Score — here the allocation-
strategy key (most/least-allocated over the mask's free CPU), which is
exactly how the reference wires NUMAAllocateStrategy into hint scores —
then lowest mask id for determinism.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.api.extension import (
    NUMA_POLICY_BEST_EFFORT as POLICY_BEST_EFFORT,
    NUMA_POLICY_NONE as POLICY_NONE,
    NUMA_POLICY_RESTRICTED as POLICY_RESTRICTED,
    NUMA_POLICY_SINGLE_NUMA_NODE as POLICY_SINGLE_NUMA_NODE,
    numa_policy_code as policy_code,
)
from koordinator_tpu.scheduler.batching import EPS


@functools.lru_cache(maxsize=None)
def mask_table(n_zones: int) -> Tuple[np.ndarray, np.ndarray]:
    """(masks bool[M, Z], popcount i32[M]) for M = 2^Z candidate
    affinities; row id == bitmask value, row 0 is the empty mask."""
    m = 1 << n_zones
    ids = np.arange(m, dtype=np.uint32)
    masks = (ids[:, None] >> np.arange(n_zones, dtype=np.uint32)) & 1
    masks = masks.astype(bool)
    return masks, masks.sum(axis=1).astype(np.int32)


def capacity_hints(free_z: jnp.ndarray, req: jnp.ndarray,
                   valid: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The CPU+memory provider (NodeNUMAResource GetPodTopologyHints):
    free_z f32[P, Z, D], req f32[P, D], valid bool[P, Z] ->
    (fit, pref) bool[P, M].

    A mask fits when it uses only valid zones and its combined free covers
    every dimension; pods with zero request have no preference (all masks
    fit and are preferred — the nil-hint row of policy.go
    filterProvidersHints).
    """
    z = free_z.shape[1]
    masks_np, popcnt_np = mask_table(z)
    masks = jnp.asarray(masks_np)                            # [M, Z]
    popcnt = jnp.asarray(popcnt_np)                          # [M]
    avail = jnp.einsum("pzd,mz->pmd", free_z * valid[:, :, None],
                       masks.astype(free_z.dtype))           # [P, M, D]
    fit = jnp.all(avail + EPS >= req[:, None, :], axis=-1)   # [P, M]
    # mask must lie within the node's valid zones
    inside = ~jnp.any(masks[None] & ~valid[:, None, :], axis=-1)
    fit &= inside & (popcnt > 0)[None]
    min_cnt = jnp.min(jnp.where(fit, popcnt[None], z + 1), axis=-1)
    pref = fit & (popcnt[None] == min_cnt[:, None])
    no_request = jnp.all(req <= EPS, axis=-1)
    dontcare = jnp.ones_like(fit)
    fit = jnp.where(no_request[:, None], dontcare, fit)
    pref = jnp.where(no_request[:, None], dontcare, pref)
    return fit, pref


def count_hints(zone_counts: jnp.ndarray, need: jnp.ndarray
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The DeviceShare provider (deviceshare topology hints): zone_counts
    i32[P, Z] fitting instances per zone of the chosen node, need i32[P]
    instances -> (fit, pref) bool[P, M]. need == 0 pods have no
    preference."""
    z = zone_counts.shape[1]
    masks_np, popcnt_np = mask_table(z)
    masks = jnp.asarray(masks_np)
    popcnt = jnp.asarray(popcnt_np)
    have = jnp.einsum("pz,mz->pm", zone_counts.astype(jnp.int32),
                      masks.astype(jnp.int32))               # [P, M]
    fit = (have >= need[:, None]) & (popcnt > 0)[None]
    min_cnt = jnp.min(jnp.where(fit, popcnt[None], z + 1), axis=-1)
    pref = fit & (popcnt[None] == min_cnt[:, None])
    none = need <= 0
    dontcare = jnp.ones_like(fit)
    fit = jnp.where(none[:, None], dontcare, fit)
    pref = jnp.where(none[:, None], dontcare, pref)
    return fit, pref


def merge_hints(hints: List[Tuple[jnp.ndarray, jnp.ndarray]]
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """AND across providers (policy.go mergePermutation: affinity is the
    bitwise AND, preferred only when all are preferred)."""
    fit, pref = hints[0]
    for f, p in hints[1:]:
        fit = fit & f
        pref = pref & p
    return fit, pref & fit


def resolve(fit: jnp.ndarray, pref: jnp.ndarray, policy: jnp.ndarray,
            free_cpu_z: jnp.ndarray, valid: jnp.ndarray,
            strategy: str = "most"
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-pod policy outcome.

    Args: (fit, pref) bool[P, M] merged hints, policy i32[P] effective
    policy code, free_cpu_z f32[P, Z] live free CPU per zone (the hint-
    Score strategy key), valid bool[P, Z].
    Returns (affinity bool[P, Z], admit bool[P], engaged bool[P]):
    - engaged: the topology manager constrains this pod (policy != none)
    - admit: policy admission (canAdmitPodResult per policy); none/best-
      effort always admit, restricted needs a preferred merged hint,
      single-numa-node a preferred single-zone hint. Capacity ("no mask
      fits at all") is NOT folded in here — the caller's greedy take +
      prefix gates enforce it exactly.
    - affinity: the best hint's zones; all valid zones for none-policy or
      when nothing fits (so capacity gates, not the mask, reject).
    """
    p, m = fit.shape
    z = free_cpu_z.shape[1]
    masks_np, popcnt_np = mask_table(z)
    masks = jnp.asarray(masks_np)
    popcnt = jnp.asarray(popcnt_np)

    single = (popcnt == 1)[None]                             # [1, M]
    cand = {
        POLICY_BEST_EFFORT: fit,
        POLICY_RESTRICTED: fit & pref,
        POLICY_SINGLE_NUMA_NODE: fit & pref & single,
    }
    # strategy key per mask: total free CPU over the mask's zones,
    # normalised to [0, 1); most-allocated prefers the least-free mask
    mask_free = jnp.einsum("pz,mz->pm", free_cpu_z,
                           masks.astype(free_cpu_z.dtype))
    denom = jnp.maximum(jnp.max(mask_free, axis=-1, keepdims=True), 1.0)
    strat = mask_free / (denom * (1.0 + EPS))
    if strategy != "most":
        strat = 1.0 - strat
    # minimise: non-preferred, then popcount, then strategy, then mask id
    base_key = (~pref) * (4.0 * m * (z + 2)) + popcnt[None] * (4.0 * m) \
        + strat * (2.0 * m) + jnp.arange(m)[None] * (1.0 / m)

    engaged = policy > POLICY_NONE
    admit = jnp.ones((p,), bool)
    best_mask = jnp.tile(valid, (1, 1))                      # default: all
    for code, c in cand.items():
        key = jnp.where(c, base_key, jnp.inf)
        idx = jnp.argmin(key, axis=-1)
        any_c = jnp.any(c, axis=-1)
        chosen = jnp.where(any_c[:, None], masks[idx], valid)
        is_pol = policy == code
        best_mask = jnp.where(is_pol[:, None], chosen, best_mask)
        if code == POLICY_RESTRICTED:
            admit &= ~is_pol | jnp.any(fit & pref, axis=-1) \
                | ~jnp.any(fit, axis=-1)
        elif code == POLICY_SINGLE_NUMA_NODE:
            admit &= ~is_pol | jnp.any(fit & pref & single, axis=-1) \
                | ~jnp.any(fit, axis=-1)
    # restricted/single-numa with SOME fitting mask but none admissible is
    # a policy rejection; with NO fitting mask the capacity gates reject,
    # keeping "policy admit" and "capacity" failures distinct like the
    # reference's Unschedulable statuses
    affinity = jnp.where(engaged[:, None], best_mask, valid)
    return affinity, admit, engaged


def greedy_take(free_z: jnp.ndarray, req: jnp.ndarray,
                affinity: jnp.ndarray, strategy: str = "most"
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Split req across the affinity's zones greedily in strategy order.

    free_z f32[P, Z, D] live free at the chosen node, req f32[P, D],
    affinity bool[P, Z] -> (take f32[P, Z, D], filled bool[P]).

    Zones are filled in allocation-strategy order (most-allocated packs
    the fullest zone first), each dimension independently — the batched
    equivalent of the reference allocating cpusets/memory per NUMA node
    inside the merged affinity (resource_manager.go Allocate). `filled`
    is False when the affinity's combined free cannot cover the request.
    """
    avail = jnp.where(affinity[:, :, None], free_z, 0.0)     # [P, Z, D]
    key = free_z[..., 0]                                     # free cpu
    key = jnp.where(affinity, key, jnp.inf if strategy == "most"
                    else -jnp.inf)
    order = jnp.argsort(key, axis=-1)                        # [P, Z]
    if strategy != "most":
        order = order[:, ::-1]
    sorted_avail = jnp.take_along_axis(avail, order[:, :, None], axis=1)
    cum = jnp.cumsum(sorted_avail, axis=1)
    before = cum - sorted_avail
    want = jnp.maximum(req[:, None, :] - before, 0.0)
    sorted_take = jnp.minimum(want, sorted_avail)
    take = jnp.zeros_like(sorted_take).at[
        jnp.arange(order.shape[0])[:, None], order].set(sorted_take)
    filled = jnp.all(jnp.sum(take, axis=1) + EPS >= req, axis=-1)
    return take, filled
