"""frameworkext: the extender seam around the batched scheduling core —
cycle watchdog, live score introspection, plugin service endpoints, and the
sidecar-facing scheduler service.

Capability parity with pkg/scheduler/frameworkext (SURVEY.md 2.1):
- SchedulerMonitor (scheduler_monitor.go:40-52): records each batch's
  start; a completion past the timeout logs a warning and increments a
  counter; overdue in-flight cycles are queryable (the watchdog thread).
- Debug score tables (debug.go:42-59): when enabled, every scheduled batch
  dumps a pretty-printed top-N nodes-by-score table per pod — the direct
  fixture for eyeballing the TPU score matrix.
- Services (services/): every registered provider serves its summary at
  /apis/v1/plugins/<name> on a plain HTTP endpoint; /debug/flags/s toggles
  the score dump at runtime like the reference's DebugScoresSetter.
- SchedulerService: the seam the control-plane edge calls (the gRPC
  sidecar boundary per BASELINE.json): holds the SnapshotStore, schedules
  pod batches chunk-by-chunk against the current snapshot, publishes the
  post-commit snapshot, and reports through the monitor/debug hooks.
- Resilience layer (docs/DESIGN.md "Failure model & degradation
  ladder"): device health guards fused into every batch program
  (scheduler/guards.py), typed failure classification with bounded
  monotonic backoff (errorhandler.classify_failure/Backoff), and the
  DegradationLadder below — the explicit rungs between "all healthy"
  and "crash", with automatic probing back up after clean cycles.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.utils.httpserver import (
    BackgroundHTTPServer,
    QuietJsonHandler,
)

from koordinator_tpu.metrics import kernel_timer
from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.obs.memwatch import MemWatch
from koordinator_tpu.obs.slo import SloTracker
from koordinator_tpu.obs.trace import NOOP_SPAN, Tracer
from koordinator_tpu.scheduler import core, guards
from koordinator_tpu.scheduler.errorhandler import (
    Backoff,
    FailureClass,
    RetryPolicy,
    TRANSIENT_CLASSES,
    classify_failure,
)
from koordinator_tpu.scheduler.journal import JournalConflict
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch
from koordinator_tpu.snapshot.store import SnapshotStore
from koordinator_tpu.utils.sync import guarded_by

log = logging.getLogger(__name__)


@guarded_by(
    _inflight="_lock",
    _seq="_lock",
    timeouts="_lock",
    timeout="publish-once",
    metrics="publish-once",
)
class SchedulerMonitor:
    """Per-batch cycle watchdog."""

    def __init__(self, timeout_seconds: float = 30.0,
                 metrics: Optional[SchedulerMetrics] = None):
        self.timeout = timeout_seconds
        self.timeouts = 0
        self.metrics = metrics
        self._lock = threading.Lock()
        self._inflight: Dict[int, float] = {}
        self._seq = 0

    def start_cycle(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._seq += 1
            self._inflight[self._seq] = now
            return self._seq

    def complete_cycle(self, token: int,
                       now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            started = self._inflight.pop(token, now)
            elapsed = now - started
            if elapsed > self.timeout:
                # inside the lock: concurrent sidecar cycles would
                # otherwise lose timeout increments
                self.timeouts += 1
        if elapsed > self.timeout:
            if self.metrics is not None:
                self.metrics.scheduling_timeout.labels("default").inc()
            log.warning("scheduling cycle exceeded %.0fs: %.2fs",
                        self.timeout, elapsed)
        return elapsed

    def overdue(self, now: Optional[float] = None) -> List[int]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [t for t, s in self._inflight.items()
                    if now - s > self.timeout]


class _CommittedCycleError(Exception):
    """A failure AFTER a cycle's snapshot commit (post-commit hooks):
    terminal by construction — retrying would schedule the same batch
    against its own post-commit snapshot and double-charge every
    placement. schedule() unwraps and re-raises the cause."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


@dataclasses.dataclass(frozen=True)
class LadderState:
    """The configuration one scheduling cycle runs at."""

    level: int = 0         # index into DegradationLadder.LEVELS
    chunk_splits: int = 0  # batch scheduled as 2**splits sequential chunks

    @property
    def cascade_off(self) -> bool:
        return self.level >= DegradationLadder.L_NO_CASCADE

    @property
    def chunked(self) -> bool:
        return self.chunk_splits > 0

    @property
    def mesh_shrink(self) -> bool:
        return self.level == DegradationLadder.L_MESH_SHRINK

    @property
    def single_device(self) -> bool:
        return self.level >= DegradationLadder.L_SINGLE_DEVICE

    @property
    def degraded(self) -> bool:
        return self.level > 0 or self.chunk_splits > 0

    def label(self) -> str:
        name = DegradationLadder.LEVELS[self.level]
        if self.chunk_splits > 0:
            name += f"/2^{self.chunk_splits}"
        return name


@guarded_by(
    # see the class docstring: the ladder is cycle machinery — the
    # service mutates it only between program attempts of one cycle
    level="confined",
    chunk_splits="confined",
    clean_streak="confined",
    degraded_cycles="confined",
    transitions="confined",
    probe_after="publish-once",
    max_chunk_splits="publish-once",
)
class DegradationLadder:
    """The explicit ladder between "all healthy" and "crash".

    Rungs, in degradation order (each rung keeps the degradations of the
    rungs above it):
      normal        -> the caller's full configuration
      no_cascade    -> cascade=False: the conformance-oracle program —
                       structurally simpler (no stage-2 narrowing), the
                       first thing to try when the full program misbehaves
      chunked       -> the batch runs as 2**chunk_splits sequential
                       sub-batches (counts and the snapshot carried
                       chunk-to-chunk); each further OOM halves again
      mesh_shrink   -> the cycle runs on a mesh rebuilt over the
                       SURVIVING devices (parallel/mesh.py pad helpers
                       re-shard the snapshot per cycle); placements
                       stay bit-identical to the full-mesh program —
                       losing 1 of 8 chips costs capacity, not a whole
                       mesh. Reached only by DEVICE_LOST with >= 2
                       survivors; a probe-up restores the full mesh.
      single_device -> inputs pinned to device 0 (the mesh is
                       abandoned until the fleet heals)

    Transitions are keyed on FailureClass: RESOURCE_EXHAUSTED jumps
    straight to chunking (retrying an identical OOM is useless),
    DEVICE_LOST goes to mesh-shrink when >= 2 devices survive (else
    single-device), everything else steps one rung — skipping
    mesh_shrink, which is meaningless without a lost device. After
    `probe_after` consecutive clean cycles below normal, ONE cycle
    probes the rung above; success commits the promotion, failure falls
    straight back (and the streak restarts). Every transition is
    recorded so the chaos matrix can assert the exact path taken.

    Not thread-safe by itself: the service mutates it only while holding
    its cycle machinery (transitions happen between program attempts).
    """

    LEVELS = ("normal", "no_cascade", "chunked", "mesh_shrink",
              "single_device")
    (L_NORMAL, L_NO_CASCADE, L_CHUNKED, L_MESH_SHRINK,
     L_SINGLE_DEVICE) = range(5)

    def __init__(self, probe_after: int = 8, max_chunk_splits: int = 4):
        self.probe_after = probe_after
        self.max_chunk_splits = max_chunk_splits
        self.level = self.L_NORMAL
        self.chunk_splits = 0
        self.clean_streak = 0
        self.degraded_cycles = 0
        self.transitions: List[Tuple[str, str]] = []  # (cause, new label)

    def state(self) -> LadderState:
        return LadderState(self.level, self.chunk_splits)

    def _probe_target(self) -> LadderState:
        if self.level == self.L_CHUNKED and self.chunk_splits > 1:
            return LadderState(self.level, self.chunk_splits - 1)
        if self.level == self.L_SINGLE_DEVICE:
            return LadderState(self.L_MESH_SHRINK, self.chunk_splits)
        if self.level == self.L_MESH_SHRINK:
            # the probe that restores the FULL mesh: back to the
            # chunked rung when chunking was in force, else straight
            # past it (a chunk-free mesh_shrink never chunked)
            if self.chunk_splits > 0:
                return LadderState(self.L_CHUNKED, self.chunk_splits)
            return LadderState(self.L_NO_CASCADE, 0)
        if self.level == self.L_CHUNKED:
            return LadderState(self.L_NO_CASCADE, 0)
        return LadderState(max(self.level - 1, 0), 0)

    def begin_cycle(self) -> Tuple[LadderState, bool]:
        """-> (state to run at, whether this cycle is an up-probe)."""
        if self.level > self.L_NORMAL \
                and self.clean_streak >= self.probe_after:
            return self._probe_target(), True
        return self.state(), False

    def on_success(self, probing: bool, state: LadderState) -> None:
        if probing:
            # commit the promotion; earn the next probe from scratch
            self._transition("probe_up", state)
            self.clean_streak = 0
        else:
            self.clean_streak += 1

    def on_failure(self, fc: FailureClass, probing: bool,
                   survivors: Optional[int] = None) -> bool:
        """Degrade for the failure class; returns False when there is no
        lower rung left (the caller re-raises). A failed PROBE is not a
        degradation — the pre-probe state simply stays. `survivors` is
        the surviving-device count the service observed for a
        DEVICE_LOST failure: >= 2 earns the mesh-shrink rung instead of
        abandoning the mesh outright (None — a caller without device
        visibility — degrades conservatively to single-device)."""
        self.clean_streak = 0
        if probing:
            return True
        if fc is FailureClass.RESOURCE_EXHAUSTED:
            if self.level < self.L_CHUNKED:
                nxt = LadderState(self.L_CHUNKED, 1)
            elif self.chunk_splits < self.max_chunk_splits:
                nxt = LadderState(self.level, self.chunk_splits + 1)
            else:
                return False
        elif fc is FailureClass.DEVICE_LOST:
            if survivors is not None and survivors >= 2 \
                    and self.level < self.L_MESH_SHRINK:
                nxt = LadderState(self.L_MESH_SHRINK, self.chunk_splits)
            elif self.level >= self.L_SINGLE_DEVICE:
                return False
            else:
                nxt = LadderState(self.L_SINGLE_DEVICE, self.chunk_splits)
        else:
            if self.level >= self.L_SINGLE_DEVICE:
                return False
            new_level = self.level + 1
            if new_level == self.L_MESH_SHRINK:
                # mesh_shrink is the DEVICE_LOST rung; a generic
                # failure that already exhausted chunking goes past it
                new_level = self.L_SINGLE_DEVICE
            nxt = LadderState(
                new_level,
                max(self.chunk_splits, 1)
                if new_level >= self.L_CHUNKED else self.chunk_splits)
        self._transition(fc.value, nxt)
        return True

    def _transition(self, cause: str, nxt: LadderState) -> None:
        self.level = nxt.level
        self.chunk_splits = nxt.chunk_splits
        self.transitions.append((cause, nxt.label()))


def debug_score_table(snap: ClusterSnapshot, pods: PodBatch,
                      cfg: LoadAwareConfig, top_n: int = 5,
                      pod_names: Optional[List[str]] = None) -> str:
    """Top-N nodes by summed plugin score per pod (debug.go:61
    debugScores) recomputed from the snapshot with the same kernels the
    commit loop uses."""
    from koordinator_tpu.scheduler.plugins import (
        deviceshare,
        loadaware,
        numaaware,
    )

    scores = np.asarray(loadaware.score_matrix(snap.nodes, pods, cfg))
    scores = scores + np.asarray(numaaware.numa_score_matrix(
        snap.nodes, pods))
    if snap.devices.gpu_free.shape[1] > 0:
        scores = scores + np.asarray(
            deviceshare.score_matrix(snap.devices, pods))
    feasible = (np.asarray(loadaware.filter_mask(snap.nodes, pods, cfg))
                & np.asarray(snap.nodes.schedulable)[None, :])
    forbid, penalty = _taint_matrices(snap, pods)
    if forbid is not None:
        feasible &= ~forbid
        scores = np.maximum(scores - penalty, 0.0)
    scores = np.where(feasible, scores, -1.0)
    lines = []
    p = pods.num_pods
    for i in range(p):
        name = pod_names[i] if pod_names else f"pod[{i}]"
        order = np.argsort(-scores[i])[:top_n]
        cells = " | ".join(f"node{int(n)}:{scores[i, n]:.1f}"
                           for n in order if scores[i, n] >= 0)
        lines.append(f"{name:<24} | {cells}")
    header = f"{'pod':<24} | top-{top_n} nodes by score"
    return "\n".join([header, "-" * len(header)] + lines)


def _taint_matrices(snap: ClusterSnapshot, pods: PodBatch):
    """(forbid [P, N], penalty [P, N]) from the TaintToleration matrices,
    or (None, None) for a batch without taint modeling — the same math
    the batch kernel applies (core.py use_taints block)."""
    if not pods.has_taints:
        return None, None
    tid = np.maximum(np.asarray(pods.toleration_id), 0)
    tg = np.asarray(snap.nodes.taint_group)
    forbid = np.asarray(pods.tol_forbid)[tid][:, tg]
    prefer = np.asarray(pods.tol_prefer)[tid][:, tg]
    max_cnt = max(float(np.asarray(pods.tol_prefer).max()), 1.0)
    from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
    return forbid, prefer / max_cnt * MAX_NODE_SCORE


def debug_filter_table(snap: ClusterSnapshot, pods: PodBatch,
                       cfg: LoadAwareConfig,
                       pod_names: Optional[List[str]] = None) -> str:
    """Per-pod filter diagnosis (debug.go DebugFiltersSetter
    /debug/flags/f): how many nodes each gate rejects, recomputed from
    the snapshot with the same prefilter kernels the batch uses — the
    per-plugin failure breakdown the reference prints per pod."""
    from koordinator_tpu.scheduler.plugins import (
        deviceshare,
        loadaware,
        numaaware,
    )

    nodes = snap.nodes
    n = int(nodes.num_nodes)
    gates: List[tuple] = []
    gates.append(("Unschedulable",
                  np.broadcast_to(np.asarray(nodes.schedulable)[None, :],
                                  (pods.num_pods, n))))
    alloc = np.asarray(nodes.allocatable)
    req = np.asarray(pods.requests)
    fit = np.all(req[:, None, :] + np.asarray(nodes.requested)[None]
                 <= alloc[None] + 1e-3, axis=-1)
    gates.append(("NodeResourcesFit", fit))
    gates.append(("LoadAwareScheduling",
                  np.asarray(loadaware.filter_mask(nodes, pods, cfg))))
    forbid, _ = _taint_matrices(snap, pods)
    if forbid is not None:
        gates.append(("TaintToleration", ~forbid))
    if pods.has_spread:
        # carrier-matrix gating (multi-constraint pods) — mirrors core
        dom_all = np.asarray(pods.spread_domain)           # [Sg, N]
        counts = np.asarray(pods.spread_count0)
        dvalid = np.asarray(pods.spread_dvalid)
        skew = np.asarray(pods.spread_max_skew)
        soft = ~np.isfinite(skew)
        min_c = np.min(np.where(dvalid, counts, np.inf), axis=1)
        min_c = np.where(np.isfinite(min_c), min_c, 0.0)
        cnt_at = np.where(dom_all >= 0,
                          np.take_along_axis(counts,
                                             np.maximum(dom_all, 0),
                                             axis=1), 0.0)
        ok_map = soft[:, None] | ((dom_all >= 0)
                                  & (cnt_at + 1.0 - min_c[:, None]
                                     <= skew[:, None] + 1e-3))
        blocked = (np.asarray(pods.spread_carrier).astype(float)
                   @ (~ok_map).astype(float)) > 0.5
        gates.append(("PodTopologySpread", ~blocked))
    if pods.has_anti:
        # (a) per-group occupancy gated by the CARRIER matrix (a pod
        # carrying several terms is gated by each — mirrors core.py)
        dom_all = np.asarray(pods.anti_domain)
        occ_a = np.where(dom_all >= 0,
                         np.take_along_axis(
                             np.asarray(pods.anti_count0),
                             np.maximum(dom_all, 0), axis=1), 0.0) > 0.5
        blocked_a = (np.asarray(pods.anti_carrier).astype(float)
                     @ occ_a.astype(float)) > 0.5
        # direction (b): matching pods avoid carrier domains
        carr = np.asarray(pods.anti_carrier_count0)
        occ = np.where(dom_all >= 0,
                       np.take_along_axis(carr, np.maximum(dom_all, 0),
                                          axis=1), 0.0) > 0.5
        blocked = (np.asarray(pods.anti_member).astype(float)
                   @ occ.astype(float)) > 0.5
        gates.append(("InterPodAntiAffinity", ~blocked_a & ~blocked))
    if pods.has_aff:
        # carrier-matrix gating with per-(pod, group) bootstrap
        dom_all = np.asarray(pods.aff_domain)              # [Fg, N]
        counts = np.asarray(pods.aff_count0)
        carrier = np.asarray(pods.aff_carrier)
        member = np.asarray(pods.aff_member)
        total = counts.sum(axis=1)
        cc_map = np.where(dom_all >= 0,
                          np.take_along_axis(counts,
                                             np.maximum(dom_all, 0),
                                             axis=1), 0.0)
        boot_pg = carrier & member & (total < 0.5)[None, :]
        bad_nonboot = ((dom_all < 0) | (cc_map <= 0.5)).astype(float)
        bad_boot = (dom_all < 0).astype(float)
        blocked = ((carrier & ~boot_pg).astype(float) @ bad_nonboot
                   + boot_pg.astype(float) @ bad_boot) > 0.5
        gates.append(("InterPodAffinity", ~blocked))
    if np.asarray(nodes.numa_valid).any():
        gates.append(("NodeNUMAResource",
                      np.asarray(numaaware.zone_prefilter(nodes, pods))))
    if snap.devices.gpu_free.shape[1] > 0:
        gates.append(("DeviceShare",
                      np.asarray(deviceshare.prefilter(snap.devices,
                                                       pods))))
    lines = []
    for i in range(pods.num_pods):
        name = pod_names[i] if pod_names else f"pod[{i}]"
        feasible = np.ones((n,), bool)
        cells = []
        for gate_name, mask in gates:
            rejected = int((~mask[i] & feasible).sum())
            feasible &= mask[i]
            if rejected:
                cells.append(f"{gate_name}:-{rejected}")
        cells.append(f"fit:{int(feasible.sum())}/{n}")
        lines.append(f"{name:<24} | {' '.join(cells)}")
    header = f"{'pod':<24} | nodes rejected per gate"
    return "\n".join([header, "-" * len(header)] + lines)


class ServiceRegistry:
    """APIServiceProvider registry: name -> summary() (services.go:44-51)."""

    def __init__(self):
        self._providers: Dict[str, Callable[[], dict]] = {}

    def register(self, name: str, summary: Callable[[], dict]) -> None:
        self._providers[name] = summary

    def names(self) -> List[str]:
        return sorted(self._providers)

    def summary(self, name: str) -> Optional[dict]:
        fn = self._providers.get(name)
        return fn() if fn is not None else None


class DebugFlags:
    """Runtime debug toggles (debug.go DebugScoresSetter /debug/flags/s)."""

    def __init__(self):
        self.score_top_n = 0     # 0 = disabled
        self.filter_dump = False  # /debug/flags/f (DebugFiltersSetter)


class ServicesServer:
    """HTTP endpoint: /apis/v1/plugins/<name> summaries, /debug/flags/s,
    and Prometheus-format /metrics exposition."""

    def __init__(self, registry: ServiceRegistry, flags: DebugFlags,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics_registry=None):
        if metrics_registry is None:
            from koordinator_tpu.metrics import global_registry
            metrics_registry = global_registry()
        registry_ref, flags_ref = registry, flags
        metrics_ref = metrics_registry

        class Handler(QuietJsonHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    self.reply_raw(200, "text/plain; version=0.0.4",
                                   metrics_ref.expose().encode())
                    return
                if self.path == "/apis/v1/plugins":
                    self.reply_json(200, {"plugins": registry_ref.names()})
                    return
                prefix = "/apis/v1/plugins/"
                if self.path.startswith(prefix):
                    summary = registry_ref.summary(self.path[len(prefix):])
                    if summary is None:
                        self.reply_json(404, {"error": "unknown plugin"})
                    else:
                        self.reply_json(200, summary)
                    return
                self.reply_json(404, {"error": "not found"})

            def do_PUT(self):
                if self.path.startswith("/debug/flags/s"):
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode().strip()
                    try:
                        flags_ref.score_top_n = int(raw or "0")
                    except ValueError:
                        self.reply_json(400, {"error": f"bad value {raw!r}"})
                        return
                    self.reply_json(200,
                                    {"scoreTopN": flags_ref.score_top_n})
                    return
                if self.path.startswith("/debug/flags/f"):
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode().strip().lower()
                    flags_ref.filter_dump = raw in ("1", "true", "on")
                    self.reply_json(200,
                                    {"filterDump": flags_ref.filter_dump})
                    return
                self.reply_json(404, {"error": "not found"})

        self._server = BackgroundHTTPServer(Handler, host, port)
        self.port = self._server.port

    def close(self) -> None:
        self._server.close()


@guarded_by(
    # batch commits: snapshot read -> device program -> publish, plus
    # all journal/epoch bookkeeping, serialize under the commit lock
    epoch="_commit_lock",
    _own_epochs="_commit_lock",
    _forced_chunks="_commit_lock",
    _cycle_digest="_commit_lock",
    _cycle_base_version="_commit_lock",
    _cycle_replayed="_commit_lock",
    _cycle_state="_commit_lock",
    _last_mesh_size="_commit_lock",
    last_committed_version="_commit_lock",
    schedule_kwargs="_commit_lock",
    # post-commit throughput counters get their own cheap lock so
    # readers never queue behind a device program
    batches="_counter_lock",
    pods_placed="_counter_lock",
    # per-thread (version, elapsed) handoff — see last_schedule_info
    _tls="confined",
    # shared last_* observability attrs: torn reads tolerated by
    # design (last_schedule_info is the race-free alternative)
    last_elapsed="racy-monitor",
    last_health_word="racy-monitor",
    last_quarantined_pods="racy-monitor",
    last_ladder_state="racy-monitor",
    last_gang_failed="racy-monitor",
    last_recovery="racy-monitor",
    # wiring, fixed before concurrent traffic starts
    store="publish-once",
    cfg="publish-once",
    metrics="publish-once",
    monitor="publish-once",
    flags="publish-once",
    registry="publish-once",
    auto_pack="publish-once",
    guards_enabled="publish-once",
    max_cycle_attempts="publish-once",
    ladder="publish-once",
    retry_policy="publish-once",
    _sleep="publish-once",
    fault_injection="publish-once",
    journal="publish-once",
    compile_cache="publish-once",
    tracer="publish-once",
    memwatch="publish-once",
    slo="publish-once",
    _cycle_ids="publish-once",
    device_health="publish-once",
    _explicit_amp="publish-once",
    error_dispatcher="publish-once",
    on_gang_failed="publish-once",
    on_assumed="publish-once",
)
class SchedulerService:
    """The sidecar seam: snapshot in, assignments out.

    The control-plane edge publishes snapshots (or functional deltas) into
    the store and feeds pending-pod batches; each batch runs the full
    device program, the post-commit snapshot becomes the next version, and
    the per-cycle watchdog + optional score dump observe every batch.
    """

    def __init__(self, store: Optional[SnapshotStore] = None,
                 cfg: Optional[LoadAwareConfig] = None,
                 monitor: Optional[SchedulerMonitor] = None,
                 flags: Optional[DebugFlags] = None,
                 registry: Optional[ServiceRegistry] = None,
                 metrics: Optional[SchedulerMetrics] = None,
                 ladder: Optional[DegradationLadder] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 journal=None,
                 compile_cache=None,
                 trace: Optional[Tracer] = None,
                 **schedule_kwargs):
        self.store = store or SnapshotStore()
        self.cfg = cfg if cfg is not None else LoadAwareConfig.make()
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        self.monitor = monitor or SchedulerMonitor(metrics=self.metrics)
        if self.monitor.metrics is None:
            self.monitor.metrics = self.metrics
        self.flags = flags or DebugFlags()
        self.registry = registry or ServiceRegistry()
        # auto_pack: derive the batching-layer specializations the bench
        # uses — domain classes for same-topologyKey groups, and the
        # topo/numa/gpu prefix packing contracts — per batch, invisibly
        # to callers (results come back in the caller's pod order).
        # Prefix widths are bucketed to powers of two so steady-state
        # traffic compiles a handful of program variants, not one per
        # constrained-count.
        self.auto_pack = bool(schedule_kwargs.pop("auto_pack", True))
        # resilience layer (docs/DESIGN.md "Failure model & degradation
        # ladder"): health guards fused into the batch program, typed
        # failure classification with bounded backoff, and the explicit
        # degradation ladder between "all healthy" and "crash"
        self.guards_enabled = bool(schedule_kwargs.pop("guards", True))
        self.max_cycle_attempts = int(
            schedule_kwargs.pop("max_cycle_attempts", 8))
        self.ladder = ladder or DegradationLadder()
        self.retry_policy = retry_policy or RetryPolicy()
        self._sleep: Callable[[float], None] = time.sleep
        # chaos seam (koordinator_tpu.testing.faults): called with
        # (LadderState, PodBatch) before every program attempt; a raised
        # exception injects a device-program failure deterministically
        self.fault_injection: Optional[Callable] = None
        # crash-recoverable scheduling (docs/DESIGN.md "Crash recovery
        # & mesh elasticity"): an optional CommitJournal makes every
        # chunk commit durable with append-before-publish ordering —
        # committed chunks of an interrupted batch replay bit-identical
        # on resume (in-process retry OR restart via recover()), and
        # uncommitted chunks are simply scheduled. Epochs are assigned
        # per batch under the commit lock, resuming where the journal
        # left off.
        self.journal = journal
        # warm-start layer (docs/DESIGN.md "Compile cache & columnar
        # packing"): an optional, STRICTLY OPT-IN compilecache handle.
        # With one attached, every cycle's device program is ensured
        # through the cache before dispatch — recover() replays and
        # mesh-shrink/chunked rung transitions then reuse AOT-compiled
        # executables (a dict lookup once warm) instead of cold-jitting
        # at the worst possible moment. None (the default) changes
        # nothing: no process-global cache config is ever touched.
        self.compile_cache = compile_cache
        if compile_cache is not None:
            compile_cache.activate()
        # koordtrace (docs/OBSERVABILITY.md): an optional span tracer.
        # None (the default) keeps the dispatch path allocation-free —
        # every span site routes through _span(), which returns the
        # shared NOOP_SPAN singleton when tracing is off. With a tracer
        # attached, closed spans feed scheduler_cycle_phase_seconds and
        # ring overflow feeds scheduler_trace_spans_dropped unless the
        # caller wired its own hooks.
        self.tracer = Tracer() if trace is True else trace
        if self.tracer is not None:
            if self.tracer.observer is None:
                self.tracer.observer = (
                    lambda name, dur:
                    self.metrics.cycle_phase_seconds
                        .labels(name).observe(dur))
            if self.tracer.on_drop is None:
                self.tracer.on_drop = self.metrics.trace_spans_dropped.inc
        # koordcost runtime plane (docs/OBSERVABILITY.md): both knobs
        # STRICTLY OPT-IN, exactly like the tracer — None (the default)
        # adds zero work to the cycle path. memwatch samples device
        # memory at the dispatch/device_wait span boundaries and runs
        # the leak sentinel per committed cycle; slo turns the cycle
        # and placement series into error-budget burn. Both surface
        # through health().
        memwatch = schedule_kwargs.pop("memwatch", None)
        if memwatch is True:
            memwatch = MemWatch(metrics=self.metrics)
        self.memwatch: Optional[MemWatch] = memwatch or None
        slo = schedule_kwargs.pop("slo", None)
        if slo is True:
            slo = SloTracker(self.metrics)
        self.slo: Optional[SloTracker] = slo or None
        # trace cycle ids: a process-monotonic sequence assigned per
        # schedule() call (itertools.count: one atomic bump per cycle)
        self._cycle_ids = itertools.count()
        self.epoch = journal.next_epoch() if journal is not None else 0
        # epochs whose records THIS process appended: a base-version
        # mismatch on one of these is a raced ingest between retry
        # attempts (safe to abandon — nothing published), never a
        # restart mis-rehydration
        self._own_epochs: set = set()
        self._forced_chunks: Optional[int] = None
        self._cycle_digest = 0
        self._cycle_base_version = 0
        self._cycle_replayed = 0
        self.last_recovery: Optional[dict] = None
        # device-loss visibility seam: a health prober returning the
        # SURVIVING jax devices; None = trust the runtime's view. The
        # mesh-shrink rung rebuilds its mesh over exactly this list.
        self.device_health: Optional[Callable[[], list]] = None
        self._last_mesh_size = len(jax.devices())
        self._cycle_state = LadderState()
        self.last_health_word = 0
        self.last_quarantined_pods: Optional[np.ndarray] = None
        self.last_ladder_state = LadderState()
        self.schedule_kwargs = schedule_kwargs
        self._explicit_amp = "enable_amplification" in schedule_kwargs
        self.batches = 0
        self.pods_placed = 0
        self.last_elapsed = 0.0
        # snapshot ingest and batch commits are serialized: a publish
        # landing mid-batch would otherwise be silently replaced by the
        # post-commit snapshot derived from the PREVIOUS version
        self._commit_lock = threading.Lock()
        # pre->default->post error chain; plugins (reservation writeback)
        # register filters (errorhandler_dispatcher.go)
        from koordinator_tpu.scheduler.errorhandler import (
            ErrorHandlerDispatcher,
        )
        self.error_dispatcher = ErrorHandlerDispatcher()
        # version of the last commit THIS service made (read under the
        # commit lock; `store.version` alone can already reflect another
        # thread's later commit)
        self.last_committed_version = 0
        # per-thread (version, elapsed) of the calling thread's last
        # schedule() — see last_schedule_info
        self._tls = threading.local()
        self._counter_lock = threading.Lock()
        # called with (failed_gang_indices, result) when a batch PROVES
        # strict gangs short of quorum; the gang controller un-assumes
        # their held members through store.forget with the batches it
        # retained (the immediate tier of the Permit rollback — the
        # wait-expiry timeout stays the backstop for gangs whose members
        # simply never reappear)
        self.on_gang_failed: Optional[Callable] = None
        self.last_gang_failed: Optional[np.ndarray] = None
        # called with (assignment, typed_pods, result) after each commit
        # when typed_pods was provided: the host assume-cache hook
        # (SnapshotSyncer.attach_scheduler) records placed pods so
        # rebuilds/topology deltas keep the in-flight charges
        self.on_assumed: Optional[Callable] = None
        self.registry.register("scheduler", self.summary)

    def _span(self, name: str, cycle: Optional[int] = None):
        """Open a koordtrace span, or the shared NOOP_SPAN when tracing
        is off. Deliberately takes NO attrs argument: hot-path callers
        attach attributes via the yielded dict (`as a: ... if a is not
        None`), so the disabled path allocates nothing — not even an
        empty dict."""
        t = self.tracer
        if t is None:
            return NOOP_SPAN
        return t.span(name, None, cycle)

    def _event(self, name: str, attrs: Optional[dict] = None,
               cycle: Optional[int] = None) -> None:
        if self.tracer is not None:
            self.tracer.event(name, attrs, cycle)

    def dump_trace(self, out_dir: str, prefix: str = "koordtrace",
                   formats=("chrome", "jsonl", "prom")) -> List[str]:
        """Write the span buffer (+ this service's metric registry, for
        the prom format) into `out_dir`; returns the written paths.
        Raises without a tracer attached — a silent empty dump would
        read as 'the service did nothing'."""
        if self.tracer is None:
            raise RuntimeError(
                "dump_trace: this service was built with trace=None")
        from koordinator_tpu.obs import export as obs_export

        return obs_export.dump(self.tracer, self.metrics.registry,
                               out_dir, prefix=prefix, formats=formats)

    def commit_guard(self):
        """The batch-commit lock, exposed so host-side snapshot writers
        (SnapshotSyncer) can serialize rebuild/ingest publishes with
        in-flight schedule commits: an unserialized rebuild landing
        between a batch's snapshot read and its post-commit publish
        would be silently overwritten (lost update), and the assume
        hook would resolve result rows against a swapped builder.
        Lock order is commit -> view, everywhere."""
        return self._commit_lock

    def surviving_devices(self) -> list:
        """The devices the service believes are healthy right now: the
        `device_health` prober's answer when one is attached, else
        whatever the runtime reports. The mesh-shrink rung builds its
        mesh over exactly this list, and DEVICE_LOST ladder decisions
        key on its length."""
        if self.device_health is not None:
            return list(self.device_health())
        return list(jax.devices())

    def publish(self, snapshot: ClusterSnapshot) -> int:
        """Returns the published version, read under the commit lock so a
        concurrent mutator cannot be misattributed."""
        with self._commit_lock:
            self.store.publish(snapshot)
            self.last_committed_version = self.store.version
            version = self.last_committed_version
        # checkpoint OUTSIDE the lock: a fsync must never stall a
        # concurrent schedule/ingest waiting on the commit lock
        self.store.maybe_checkpoint()
        return version

    def ingest(self, delta) -> int:
        """Apply an O(K) metric delta SERIALIZED with batch commits — a
        delta landing between a batch's snapshot read and its post-commit
        publish would be silently overwritten (the same hazard the commit
        lock exists for; see the lock comment above). An out-of-order /
        duplicate delta no-ops in the store's version guard; the typed
        reason lands on the scheduler_delta_rejected metric here."""
        with self._commit_lock:
            self.store.ingest(delta)
            reason = self.store.take_delta_rejection()
            if reason is not None:
                self.metrics.delta_rejected.labels(reason.value).inc()
                log.warning("delta rejected (%s): store at delta "
                            "version %d", reason.value,
                            self.store.applied_delta_version)
            self.last_committed_version = self.store.version
            version = self.last_committed_version
        self.store.maybe_checkpoint()
        return version

    # batches at or below this size schedule as-is: the quadratic
    # [P, P] savings cannot pay for the pack/unpack permutations there
    AUTO_PACK_MIN_BATCH = 512

    def _prepare_batch(self, snap: ClusterSnapshot, pods: PodBatch,
                       allow_prefix_pack: bool = True):
        """Derive the batching-layer specializations for this batch:
        `(maybe-packed pods, extra static kwargs, inverse permutation
        or None)`. Every contract the kwargs claim is established or
        verified here, host-side (the scheduler silently trusts them):
        domain classes come from actual row equality, prefixes from an
        actual pack, and numa_prefix only on a policy-free snapshot.
        `allow_prefix_pack=False` (the ladder's chunked rung) keeps the
        dom_classes derivation but skips the prefix contracts — slicing
        a prefix-packed batch into chunks would break the row-range
        claims the prefixes make."""
        from koordinator_tpu.utils import synthetic as batching

        from koordinator_tpu.scheduler.plugins import deviceshare

        kwargs = {}
        if not self.auto_pack:
            return pods, kwargs, None
        if pods.has_spread or pods.has_anti or pods.has_aff:
            classes = batching.dom_classes(pods)
            if any(len(c) > 1 for fam in classes for c in fam):
                # all-singleton partitions ARE the default program —
                # omitting them avoids a needless static-arg variant.
                # NOTE: a CHANGING partition across batches is a
                # recompile trigger (dom_classes is a static jit arg);
                # group structure is stable in steady-state traffic,
                # and auto_pack=False opts out entirely.
                kwargs["dom_classes"] = classes
        p = int(np.asarray(pods.valid).shape[0])
        if p <= self.AUTO_PACK_MIN_BATCH or not allow_prefix_pack:
            return pods, kwargs, None

        # cheap masks FIRST; the full batch copy + contract validation
        # in pack_gate_prefixes runs only when a prefix survives
        topo = batching.topo_constrained_mask(pods)
        numa = np.asarray(pods.numa_single, bool)
        gpu = np.asarray(deviceshare.has_device_request(pods), bool)

        def bucket(count):
            # power-of-two widths (>= the packer's tight 128-aligned
            # prefix by construction) bound the compile variants; a
            # class covering most of the batch is not worth a prefix
            if count == 0 or count >= p // 2:
                return None
            width = 128
            while width < count:
                width *= 2
            return min(width, p)

        want = {}
        if topo.any():
            want["topo_prefix"] = bucket(int(topo.sum()))
        if self.schedule_kwargs.get("enable_numa", True) and numa.any() \
                and not np.asarray(snap.nodes.numa_policy).any():
            want["numa_prefix"] = bucket(int((topo | numa).sum()))
        if self.schedule_kwargs.get("enable_devices", True) \
                and gpu.any():
            want["gpu_prefix"] = bucket(int((topo | numa | gpu).sum()))
        want = {k: v for k, v in want.items() if v is not None}
        if not want:
            return pods, kwargs, None  # classes alone need no reorder
        packed, _, masks = batching.pack_gate_prefixes(pods, p)
        kwargs.update(want)
        perm = masks["perm"]
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.size)
        return packed, kwargs, inv

    def _begin_journal_cycle(self, pods: PodBatch) -> None:
        """Journal bookkeeping for one cycle attempt, under the commit
        lock: capture the base version/digest the records will carry,
        and detect a RESUME — committed records already journaled for
        this epoch pin the chunk layout and must match the resubmitted
        batch (digest) and the rehydrated snapshot (base version);
        either mismatch is a terminal JournalConflict, because replay
        against different inputs would silently diverge from the
        journaled placements."""
        from koordinator_tpu.scheduler import journal as journal_mod

        self._cycle_base_version = self.store.version
        self._cycle_replayed = 0
        self._cycle_digest = journal_mod.batch_digest(pods)
        self._forced_chunks = None
        committed = self.journal.records_for(self.epoch)
        if not committed:
            return
        rec = next(iter(committed.values()))
        if rec.batch_digest != self._cycle_digest:
            raise journal_mod.JournalConflict(
                f"epoch {self.epoch} resume: the resubmitted batch's "
                f"digest {self._cycle_digest:#x} differs from the "
                f"journaled {rec.batch_digest:#x} — refusing to "
                f"complete another batch's committed chunks (if the "
                f"interrupted batch is gone for good, call "
                f"abandon_interrupted_epoch() to close its epoch)")
        if rec.base_version != self.store.version:
            if self.epoch in self._own_epochs:
                # a delta/publish landed between THIS process's retry
                # attempts (the backoff sleeps outside the commit lock
                # by design): the journaled chunks pinned placements
                # against a snapshot that no longer exists, but nothing
                # of this epoch was ever published (publish seals an
                # epoch) — so abandon them durably and re-run the whole
                # batch against the fresher snapshot, exactly what the
                # pre-journal retry did
                log.warning(
                    "epoch %d: store moved %d -> %d under an in-flight "
                    "retry; abandoning %d journaled chunk(s) and "
                    "re-running the batch fresh", self.epoch,
                    rec.base_version, self.store.version, len(committed))
                self.journal.abandon(self.epoch)
                self.epoch = self.journal.next_epoch()
                return
            raise journal_mod.JournalConflict(
                f"epoch {self.epoch} resume: store at version "
                f"{self.store.version} but the journaled chunks ran "
                f"against version {rec.base_version} — rehydrate the "
                f"store (checkpoint restore + delta/epoch replay) "
                f"before resuming")
        self._forced_chunks = rec.n_chunks

    def _ensure_cached(self, snap: ClusterSnapshot, pods: PodBatch,
                       kwargs: dict) -> None:
        """Request the cycle program from the compile cache before
        dispatch (no-op without a cache handle). The abstract signature
        is derived from the CONCRETE inputs — padded/sharded mesh-
        shrink forms and chunked sub-batch widths key distinct entries,
        exactly the transitions that used to cold-jit. Best-effort: a
        cache failure must never fail a scheduling cycle."""
        if self.compile_cache is None:
            return
        from koordinator_tpu.compilecache import precompile

        with self._span(obs_phases.SPAN_ENSURE_CACHED):
            try:
                precompile.ensure_cycle_program(
                    self.compile_cache, snap, pods, self.cfg, kwargs,
                    guarded=self.guards_enabled, metrics=self.metrics)
            except Exception:  # noqa: BLE001 — warmth is advisory
                log.warning("compile-cache ensure failed; cycle will "
                            "cold-jit", exc_info=True)

    def _run_program(self, snap: ClusterSnapshot, pods: PodBatch,
                     kwargs: dict):
        """One guarded/unguarded device-program invocation ->
        `(result, health u32[3] device array or None, node_bad,
        pod_bad)`. With guards on, detection + quarantine + scheduling
        are ONE fused program (scheduler/guards.py)."""
        if self.fault_injection is not None:
            # the chaos seam sits at the PROGRAM invocation, so chunked
            # cycles inject per sub-batch — a width-dependent OOM stops
            # firing once halving narrows below its threshold, exactly
            # like a real allocator
            self.fault_injection(self._cycle_state, pods)
        if self.guards_enabled:
            return guards.guarded_schedule_batch(snap, pods, self.cfg,
                                                 **kwargs)
        result = core.schedule_batch(snap, pods, self.cfg, **kwargs)
        return result, None, None, None

    def _journal_commit(self, chunk: int, n_chunks: int,
                        assignment: np.ndarray) -> None:
        """Durably commit one chunk's assignment (append-before-publish
        — the store has NOT published when this runs). An identical
        already-journaled record is the replay path: counted, asserted
        bit-identical inside the journal, and never re-appended — a
        committed pod is never re-placed. A divergent record raises
        JournalConflict (terminal)."""
        from koordinator_tpu.scheduler import journal as journal_mod

        rec = journal_mod.JournalRecord(
            epoch=self.epoch, chunk=chunk, n_chunks=n_chunks,
            base_version=self._cycle_base_version,
            delta_watermark=self.store.applied_delta_version,
            batch_digest=self._cycle_digest,
            assignment=np.asarray(assignment, np.int32))
        with self._span(obs_phases.SPAN_JOURNAL_APPEND) as jrn:
            wrote = self.journal.append(rec)
            if jrn is not None:
                # the trace <-> commit-log join: a journal record is
                # findable from its span and vice versa
                jrn["epoch"] = self.epoch
                jrn["chunk"] = chunk
                jrn["n_chunks"] = n_chunks
                jrn["bytes"] = int(wrote)
                jrn["replayed"] = not wrote
        if wrote:
            self._own_epochs.add(self.epoch)
            self.metrics.journal_appends.inc()
            self.metrics.journal_bytes.inc(wrote)
        else:
            self._cycle_replayed += 1

    def _run_chunked(self, snap: ClusterSnapshot, pods: PodBatch,
                     kwargs: dict, n_chunks: int):
        """The ladder's chunked rung: `n_chunks` sequential sub-batches
        against the evolving snapshot, topology counts carried
        chunk-to-chunk exactly like the bench sweep (the cross-batch
        count rule). `gang_failed` is SUPPRESSED here — per-chunk
        quorum proofs don't compose across chunks, and a false
        un-assume corrupts held capacity; the Permit wait-expiry
        timeout stays the rollback backstop for degraded cycles. All
        merging stays device-side with no per-chunk host sync — except
        under a commit journal, which by design trades one assignment
        readback per chunk for chunk-granular crash durability."""
        p = int(np.asarray(pods.valid).shape[0])
        n_chunks = max(min(n_chunks, p), 1)
        from koordinator_tpu.utils import synthetic
        sizes = [len(c) for c in np.array_split(np.arange(p), n_chunks)]
        # the whole batch on device first (one upload, like the bench
        # sweep): the count-charge helpers compose eagerly with .at
        # scatters and clipped gathers — numpy operands would raise on
        # the degenerate [1, 1] domain matrices instead of dropping
        pods = jax.device_put(pods)
        counts = tuple(getattr(pods, f) for f in core.COUNT_FIELDS)
        parts, pod_bads, node_bad, health = [], [], None, None
        start = 0
        chunk_idx = -1
        ensured_widths = set()
        for size in sizes:
            if size == 0:
                continue
            chunk_idx += 1
            batch = synthetic.slice_batch(pods, start, size)
            batch = batch.replace(**dict(zip(core.COUNT_FIELDS, counts)))
            if size not in ensured_widths:
                # one ensure per DISTINCT sub-batch width: array_split
                # yields at most two widths per layout, and every later
                # chunk of the same width is the same program
                ensured_widths.add(size)
                self._ensure_cached(snap, batch, kwargs)
            res_i, h_i, nb_i, pb_i = self._run_program(snap, batch, kwargs)
            if self.journal is not None:
                # the journaled readback is the chunk's COMMIT point
                self._journal_commit(chunk_idx, n_chunks,
                                     np.asarray(res_i.assignment))
            counts = core.charge_all_counts(counts, batch,
                                            res_i.assignment)
            snap = res_i.snapshot
            parts.append(res_i)
            if h_i is not None:
                pod_bads.append(pb_i)
                node_bad = nb_i if node_bad is None else node_bad | nb_i
                # the WORD merges bitwise; counts do not (a node bad in
                # several chunks is one bad node) — the node count is
                # recomputed from the merged mask below, pod rows are
                # disjoint so their counts sum
                health = h_i if health is None else jnp.stack(
                    [health[0] | h_i[0], health[1], health[2] + h_i[2]])
            start += size
        if health is not None:
            health = jnp.stack([health[0],
                                node_bad.sum().astype(jnp.uint32),
                                health[2]])
        merged = parts[0].replace(
            snapshot=snap,
            gang_failed=jnp.zeros_like(parts[0].gang_failed),
            **{f: jnp.concatenate([getattr(r, f) for r in parts])
               for f in core.PER_POD_RESULT_FIELDS})
        pod_bad = jnp.concatenate(pod_bads) if pod_bads else None
        return merged, health, node_bad, pod_bad

    def _device_cycle(self, snap: ClusterSnapshot, pods: PodBatch,
                      kwargs: dict, state: LadderState):
        """Run one cycle's device program at the ladder state's
        configuration. A journaled resume (`_forced_chunks`) pins the
        chunk layout to the journaled epoch's regardless of the current
        ladder state — replay must slice the batch exactly as the
        interrupted run did."""
        self._cycle_state = state
        n_real = None
        if state.single_device:
            dev = jax.devices()[0]
            snap = jax.device_put(snap, dev)
            pods = jax.device_put(pods, dev)
            self._last_mesh_size = 1
        elif state.mesh_shrink:
            # rebuild the mesh over the survivors: pad the node axis to
            # the shrunk mesh, re-shard, run — then unpad the committed
            # snapshot so stored shapes never depend on the surviving-
            # device count. Placements are bit-identical through the
            # padding/sharding path (the PR 4 mesh conformance pins).
            from koordinator_tpu.parallel import mesh as meshlib

            devs = self.surviving_devices()
            mesh = meshlib.make_mesh(devs)
            n_real = int(snap.num_nodes)
            snap = meshlib.shard_snapshot(
                meshlib.pad_nodes_to_mesh(snap, mesh), mesh)
            pods = meshlib.pad_batch_nodes(
                pods, meshlib.padded_node_count(n_real, mesh))
            self._last_mesh_size = len(devs)
        else:
            self._last_mesh_size = len(self.surviving_devices())
        if state.cascade_off:
            kwargs = dict(kwargs, cascade=False)
        if self._forced_chunks is not None:
            # the journaled layout wins over the ladder in BOTH
            # directions: a 1-chunk epoch replays as the single
            # program even on a chunked-rung service (running it
            # chunked would journal conflicting n_chunks records)
            if self._forced_chunks > 1:
                out = self._run_chunked(snap, pods, kwargs,
                                        self._forced_chunks)
            else:
                self._ensure_cached(snap, pods, kwargs)
                out = self._run_program(snap, pods, kwargs)
        elif state.chunked:
            out = self._run_chunked(snap, pods, kwargs,
                                    2 ** state.chunk_splits)
        else:
            # the normal AND mesh-shrink paths ensure here: on the
            # shrink rung `snap`/`pods` already carry the padded,
            # resharded survivor-mesh forms, so the cache key is
            # exactly the program about to dispatch
            self._ensure_cached(snap, pods, kwargs)
            out = self._run_program(snap, pods, kwargs)
        if n_real is not None:
            from koordinator_tpu.parallel import mesh as meshlib

            result, health, node_bad, pod_bad = out
            result = result.replace(
                snapshot=meshlib.unpad_nodes(result.snapshot, n_real))
            if node_bad is not None:
                node_bad = node_bad[:n_real]
            out = (result, health, node_bad, pod_bad)
        return out

    def _locked_cycle(self, pods: PodBatch, typed_pods,
                      state: LadderState):
        """The serialized snapshot-read -> program -> commit section of
        one cycle attempt."""
        with self._commit_lock:
            with self._span(obs_phases.SPAN_ADMIT) as adm:
                snap = self.store.current()
                if self.journal is not None:
                    self._begin_journal_cycle(pods)
                # amplified-CPU auto-detection happens on the snapshot
                # the batch actually runs against (an explicit
                # enable_amplification kwarg from the constructor
                # wins). Deriving here rather than at publish time
                # keeps the flag correct for writers that bypass
                # service.publish() and put snapshots straight into the
                # shared SnapshotStore (SnapshotSyncer._rebuild,
                # embedded compositions).
                if not self._explicit_amp:
                    self.schedule_kwargs["enable_amplification"] = bool(
                        np.asarray(
                            snap.nodes.cpu_amplification > 1.0).any())
                # a journaled resume (forced chunk layout) also forbids
                # prefix packing: slicing a packed batch breaks the
                # row-range contracts, exactly like the chunked rung
                sched_pods, pack_kwargs, inv = self._prepare_batch(
                    snap, pods,
                    allow_prefix_pack=not state.chunked
                    and (self._forced_chunks is None
                         or self._forced_chunks <= 1))
                if adm is not None:
                    # the trace <-> journal join at cycle granularity
                    adm["base_version"] = self.store.version
                    if self.journal is not None:
                        adm["epoch"] = self.epoch
            if self.memwatch is not None:
                # boundary sample 1: residency as the dispatch opens
                self.memwatch.sample()
            with kernel_timer(self.metrics.kernel_seconds,
                              obs_phases.PHASE_SCHEDULE_BATCH):
                with self._span(obs_phases.SPAN_DISPATCH) as dsp:
                    result, health_dev, _node_bad, pod_bad = \
                        self._device_cycle(
                            snap, sched_pods,
                            {**self.schedule_kwargs, **pack_kwargs},
                            state)
                    if dsp is not None:
                        dsp["ladder"] = state.label()
                        dsp["mesh_size"] = self._last_mesh_size
                if inv is not None:
                    # back to the CALLER's pod order before anything
                    # (hooks, error chain, debug tables) sees the result
                    result = result.replace(
                        **{f: getattr(result, f)[inv]
                           for f in core.PER_POD_RESULT_FIELDS})
                    if pod_bad is not None:
                        pod_bad = pod_bad[inv]
                # single D2H transfer doubles as the completion barrier
                # (and makes the kernel timer measure device time)
                with self._span(obs_phases.SPAN_DEVICE_WAIT):
                    assignment = np.asarray(result.assignment)
                if self.memwatch is not None:
                    # boundary sample 2: residency after the program
                    # completed — the sample the leak sentinel advances
                    # on at commit
                    self.memwatch.sample()
            # the guards' ONE packed readback ([word, bad nodes, bad
            # pods]); the full masks stay on device unless the word is
            # non-zero (cold path)
            with self._span(obs_phases.SPAN_GUARD_SCAN) as gsc:
                health = (np.asarray(health_dev)
                          if health_dev is not None else None)
                if gsc is not None:
                    gsc["guards"] = self.guards_enabled
                    if health is not None:
                        gsc["word"] = int(health[0])
            # what _device_cycle ACTUALLY ran: the journaled layout
            # overrides the ladder in both directions
            chunked_run = (self._forced_chunks > 1
                           if self._forced_chunks is not None
                           else state.chunked)
            if self.journal is not None and not chunked_run:
                # append-before-publish: the single-program cycle's one
                # record lands BEFORE the store publish below, so a
                # crash between them replays rather than loses the batch
                self._journal_commit(0, 1, assignment)
            with self._span(obs_phases.SPAN_PUBLISH) as pub:
                self.store.update(lambda _old: result.snapshot)
                if self.journal is not None:
                    # the batch committed: the epoch is sealed (its
                    # chunk set is complete in the journal) and the
                    # next schedule opens a new one; the own-epoch
                    # marker only matters for the CURRENT epoch's
                    # retries, so drop the sealed one (a resident
                    # service must not accrete the set)
                    self._own_epochs.discard(self.epoch)
                    self.epoch += 1
                    self._forced_chunks = None
                if pub is not None:
                    pub["version"] = self.store.version
            # THE COMMIT POINT: everything below ran against a snapshot
            # version that is now published. A failure past here must
            # NOT re-enter the retry loop — re-running the cycle would
            # schedule the same batch against its own post-commit
            # snapshot and double-charge every placement — so it is
            # wrapped as terminal (_CommittedCycleError).
            try:
                # THIS call's commit version, captured under the lock —
                # the shared last_committed_version attribute can
                # already reflect a racing ingest by the time a caller
                # reads it
                version = self.store.version
                self.last_committed_version = version
                if self.on_assumed is not None and typed_pods is not None:
                    # under the commit lock: an attached syncer's
                    # rebuild (which serializes on the same lock)
                    # cannot swap the builder between this batch's
                    # snapshot and the hook's row-name resolution
                    self.on_assumed(assignment, typed_pods, result)
            except Exception as exc:
                raise _CommittedCycleError(exc) from exc
            # cycle-local copies captured under the lock: by the time
            # schedule() publishes metrics, a concurrent cycle may have
            # overwritten the shared attributes
            mesh_size = self._last_mesh_size
            replayed = self._cycle_replayed
        return (snap, result, assignment, health, pod_bad, version,
                mesh_size, replayed)

    def _trace_transitions(self, n_before: int, cycle_id: int) -> None:
        """Emit one koordtrace instant event per ladder transition the
        last ladder call appended (detected by list-length delta — the
        ladder itself stays trace-free)."""
        if self.tracer is None:
            return
        for cause, label in self.ladder.transitions[n_before:]:
            self._event(obs_phases.EVENT_LADDER_TRANSITION,
                        {"cause": cause, "to": label}, cycle=cycle_id)

    def schedule(self, pods: PodBatch,
                 pod_names: Optional[List[str]] = None,
                 typed_pods: Optional[List] = None) -> core.ScheduleResult:
        """`typed_pods` (batch-ordered api.Pod list) opts unplaced rows
        into the error-handler chain — the reservation filter needs the
        typed pod to recognize reserve pods.

        Runtime failures are classified (errorhandler.classify_failure),
        transients retried with bounded monotonic backoff, and
        persistent failures walked down the degradation ladder; the
        backoff sleeps happen OUTSIDE the commit lock so publishes and
        ingests proceed while a retry waits."""
        token = self.monitor.start_cycle()
        cycle_id = next(self._cycle_ids)
        # the cycle id is unique per call (no two concurrent cycles
        # share a jitter stream) and needs no lock, unlike the batch
        # counter it used to seed from
        backoff = Backoff(self.retry_policy, seed=cycle_id)
        attempts = 0
        while True:
            n_trans = len(self.ladder.transitions)
            state, probing = self.ladder.begin_cycle()
            self._trace_transitions(n_trans, cycle_id)
            try:
                with self._span(obs_phases.SPAN_CYCLE,
                                cycle=cycle_id) as cyc:
                    if cyc is not None:
                        cyc["attempt"] = attempts
                        cyc["ladder"] = state.label()
                    (snap, result, assignment, health, pod_bad,
                     version, mesh_size,
                     replayed) = self._locked_cycle(pods, typed_pods,
                                                    state)
                n_trans = len(self.ladder.transitions)
                self.ladder.on_success(probing, state)
                self._trace_transitions(n_trans, cycle_id)
                break
            except _CommittedCycleError as exc:
                # the snapshot already committed: never retry (see
                # _CommittedCycleError), surface the hook's failure
                self.monitor.complete_cycle(token)
                raise exc.cause
            except JournalConflict:
                # the journal disagrees with this cycle's inputs:
                # terminal by construction — a retry re-derives the
                # same divergence, and degrading cannot fix a wrong
                # batch or a stale snapshot
                self.monitor.complete_cycle(token)
                raise
            except Exception as exc:
                # every device-program failure routes through the
                # FailureClass classifier (koordlint RB001)
                fc = classify_failure(exc)
                self.metrics.failures_classified.labels(fc.value).inc()
                attempts += 1
                if self.tracer is not None:
                    self._event(obs_phases.EVENT_RETRY,
                                {"failure_class": fc.value,
                                 "attempt": attempts,
                                 "ladder": state.label()},
                                cycle=cycle_id)
                log.warning(
                    "scheduling cycle failed (class=%s, attempt %d, "
                    "ladder=%s): %r", fc.value, attempts, state.label(),
                    exc)
                if attempts >= self.max_cycle_attempts:
                    self.monitor.complete_cycle(token)
                    raise
                if probing:
                    # a failed up-probe falls straight back; the
                    # pre-probe state was never left
                    n_trans = len(self.ladder.transitions)
                    self.ladder.on_failure(fc, probing=True)
                    self._trace_transitions(n_trans, cycle_id)
                    continue
                if fc in TRANSIENT_CLASSES and not backoff.exhausted():
                    delay = backoff.next_delay()
                    with self._span(obs_phases.SPAN_BACKOFF,
                                    cycle=cycle_id) as bko:
                        if bko is not None:
                            bko["failure_class"] = fc.value
                            bko["attempt"] = attempts
                            bko["delay_s"] = delay
                        self._sleep(delay)
                    continue
                survivors = None
                if fc is FailureClass.DEVICE_LOST:
                    # the ladder's DEVICE_LOST decision keys on how
                    # many devices actually survive: >= 2 earns the
                    # mesh-shrink rung, fewer abandons the mesh
                    survivors = len(self.surviving_devices())
                pre_level = self.ladder.level
                n_trans = len(self.ladder.transitions)
                if not self.ladder.on_failure(fc, probing=False,
                                              survivors=survivors):
                    # no lower rung left: the failure is terminal
                    self.monitor.complete_cycle(token)
                    raise
                self._trace_transitions(n_trans, cycle_id)
                if self.ladder.level == DegradationLadder.L_MESH_SHRINK \
                        and pre_level != DegradationLadder.L_MESH_SHRINK:
                    self.metrics.mesh_shrink_events.inc()
                backoff.reset()
        self.last_ladder_state = state
        if state.degraded or probing:
            self.metrics.degraded_cycles.labels(state.label()).inc()
        self.metrics.degradation_level.set(float(self.ladder.level))
        self.metrics.mesh_size.set(float(mesh_size))
        if self.journal is not None and replayed:
            self.metrics.recovery_replayed.inc(replayed)
        word = int(health[0]) if health is not None else 0
        self.last_health_word = word
        pod_bad_np: Optional[np.ndarray] = None
        if word:
            defects = guards.decode_health_word(word)
            for name in defects:
                self.metrics.guard_trips.labels(name).inc()
            n_bad_nodes, n_bad_pods = int(health[1]), int(health[2])
            if n_bad_nodes:
                self.metrics.quarantined_inputs.labels("node").inc(
                    n_bad_nodes)
            if n_bad_pods:
                self.metrics.quarantined_inputs.labels("pod").inc(
                    n_bad_pods)
            if pod_bad is not None:
                pod_bad_np = np.asarray(pod_bad)
            if self.tracer is not None:
                self._event(obs_phases.EVENT_QUARANTINE,
                            {"word": word, "defects": defects,
                             "bad_nodes": n_bad_nodes,
                             "bad_pods": n_bad_pods}, cycle=cycle_id)
            log.warning(
                "health guards tripped: word=0x%x (%s); %d node(s) / "
                "%d pod(s) quarantined", word, ",".join(defects),
                n_bad_nodes, n_bad_pods)
        self.last_quarantined_pods = pod_bad_np
        self.last_elapsed = elapsed = self.monitor.complete_cycle(token)
        if elapsed > self.monitor.timeout:
            # the stall completed, but the NEXT cycle runs degraded:
            # a watchdog trip is a classified failure like any other
            self.metrics.failures_classified.labels(
                FailureClass.WATCHDOG_STALL.value).inc()
            n_trans = len(self.ladder.transitions)
            self.ladder.on_failure(FailureClass.WATCHDOG_STALL,
                                   probing=False)
            self._trace_transitions(n_trans, cycle_id)
        # per-CALL (version, elapsed) for the calling thread: the
        # threaded sidecar reads them after scheduling, and the shared
        # attributes race with concurrent ingests/schedules
        self._tls.version = version
        self._tls.elapsed = elapsed
        self.metrics.cycle_seconds.observe(elapsed)
        valid = np.asarray(pods.valid)
        placed_n = int(((assignment >= 0) & valid).sum())
        with self._counter_lock:
            # += on the shared counters is not atomic across threads;
            # the threaded sidecar schedules concurrently
            self.batches += 1
            self.pods_placed += placed_n
        self.metrics.pods_scheduled.labels("placed").inc(placed_n)
        unsched = (assignment < 0) & valid
        if pod_bad_np is not None:
            # quarantined rows are infrastructure errors, already
            # counted per kind above — not "unschedulable" (cluster
            # full) rows
            unsched &= ~pod_bad_np
        self.metrics.pods_scheduled.labels("unschedulable").inc(
            int(unsched.sum()))
        self.metrics.snapshot_version.set(float(self.store.version))
        # koordcost: the cycle committed and its counters/histograms
        # are final — advance the leak sentinel and the SLO rings
        if self.memwatch is not None:
            self.memwatch.observe_cycle()
        if self.slo is not None:
            self.slo.observe_cycle()
        gang_failed = np.asarray(result.gang_failed)
        self.last_gang_failed = gang_failed
        if gang_failed.any() and self.on_gang_failed is not None:
            self.on_gang_failed(np.where(gang_failed)[0], result)
        if typed_pods is not None:
            from koordinator_tpu.scheduler.errorhandler import (
                dispatch_batch_errors,
            )
            dispatch_batch_errors(self.error_dispatcher, assignment, valid,
                                  typed_pods, infra_mask=pod_bad_np)
        if self.flags.score_top_n > 0:
            log.info("score table:\n%s", debug_score_table(
                snap, pods, self.cfg, self.flags.score_top_n, pod_names))
        if self.flags.filter_dump:
            log.info("filter table:\n%s", debug_filter_table(
                snap, pods, self.cfg, pod_names))
        # the post-commit checkpoint, outside the commit lock: a fsync
        # must never stall the next cycle's snapshot read
        with self._span(obs_phases.SPAN_CHECKPOINT,
                        cycle=cycle_id) as ckp:
            wrote_ckpt = self.store.maybe_checkpoint()
            if ckp is not None:
                ckp["wrote"] = bool(wrote_ckpt)
            if wrote_ckpt and self.journal is not None:
                # epochs below the fresh checkpoint can never replay:
                # prune them so a resident service's journal stays
                # bounded (serialized with appends via the commit lock)
                with self._commit_lock:
                    self.journal.prune(
                        self.store.last_checkpoint_version)
        return result

    def abandon_interrupted_epoch(self) -> bool:
        """Durably close the current epoch's journaled chunks with a
        tombstone and move to a fresh epoch — the unwedge path when an
        interrupted batch will NEVER be resubmitted (without this,
        every future schedule() of a different batch would refuse with
        a digest JournalConflict). Safe because an incomplete epoch
        has published nothing: dropping its chunks loses no
        externally-visible placement. Returns False when there is
        nothing to abandon."""
        if self.journal is None:
            return False
        with self._commit_lock:
            if not self.journal.records_for(self.epoch):
                return False
            self.journal.abandon(self.epoch)
            self.epoch = self.journal.next_epoch()
            return True

    def recover(self, batches,
                typed_pods_by_epoch: Optional[Dict[int, List]] = None
                ) -> dict:
        """Restart recovery: rehydrate the store, then bring the world
        back to exactly where the crash interrupted it — never
        re-placing a committed pod, never dropping an uncommitted one.

        1. If the store has no snapshot yet, restore the last
           checkpoint (version + delta high-water mark come with it).
           A caller whose producer logs deltas re-ingests them next:
           already-applied ones no-op in the store's version guard.
        2. Every journaled epoch whose base version is AT OR PAST the
           rehydrated store version re-runs through the normal
           schedule() path: committed chunks replay (the journal
           asserts them bit-identical and they are never re-appended),
           missing chunks of an interrupted tail epoch schedule fresh,
           and each epoch's publish re-derives the store state the
           crash destroyed.

        `batches` maps epoch -> the resubmitted PodBatch (or is a
        callable epoch -> PodBatch); the journal's batch digest pins
        that the resubmission is the same batch. Returns a report dict
        (also kept on `last_recovery`) with the per-epoch results."""
        if self.journal is None:
            raise RuntimeError("recover() needs a commit journal")
        from koordinator_tpu.compilecache import counters as compile_counters

        t0 = time.monotonic()
        t0_ns = time.monotonic_ns()
        restored = False
        # the whole recovery runs under a compile watcher so the
        # recorded time splits into what replay actually spent vs what
        # XLA compilation cost on top — the component a warmed compile
        # cache deletes (PR 5/6 recoveries were compile-dominated)
        with compile_counters.watch() as compile_watch:
            try:
                self.store.current()
            except RuntimeError:
                restored = self.store.restore()
                if not restored:
                    raise RuntimeError(
                        "recover(): no snapshot and no readable checkpoint "
                        "— publish the initial snapshot, then call "
                        "recover() again to replay the journal")
            epochs = [e for e in self.journal.epochs()
                      if self.journal.base_version_of(e)
                      >= self.store.version]
            results = {}
            replayed = 0
            for e in epochs:
                pods = batches(e) if callable(batches) else batches[e]
                typed = (typed_pods_by_epoch or {}).get(e)
                # epoch/bookkeeping writes take the commit lock even on
                # this (normally single-threaded) startup path: a
                # producer already re-ingesting deltas concurrently
                # must never see a half-switched epoch
                with self._commit_lock:
                    self.epoch = e
                results[e] = self.schedule(pods, typed_pods=typed)
                with self._commit_lock:
                    replayed += self._cycle_replayed
            with self._commit_lock:
                self.epoch = self.journal.next_epoch()
        seconds = time.monotonic() - t0
        compile_seconds = min(compile_watch.compile_seconds, seconds)
        replay_seconds = seconds - compile_seconds
        self.metrics.recovery_seconds.observe(seconds)
        self.metrics.recovery_compile_seconds.observe(compile_seconds)
        self.metrics.recovery_replay_seconds.observe(replay_seconds)
        if self.tracer is not None:
            # the recover span plus its replay-vs-compile split as two
            # child spans. The split is derived from the compile
            # watcher, not separately clocked, so the children are laid
            # out proportionally inside the parent (replay first) —
            # their DURATIONS are the measured truth, their ordering an
            # approximation.
            end_ns = t0_ns + int(seconds * 1e9)
            split_ns = t0_ns + int(replay_seconds * 1e9)
            self.tracer.record_span(
                obs_phases.SPAN_RECOVER, t0_ns, end_ns,
                attrs={"epochs": list(epochs),
                       "records_replayed": replayed,
                       "restored_checkpoint": restored})
            self.tracer.record_span(
                obs_phases.SPAN_RECOVER_REPLAY, t0_ns, split_ns,
                parent=obs_phases.SPAN_RECOVER)
            self.tracer.record_span(
                obs_phases.SPAN_RECOVER_COMPILE, split_ns, end_ns,
                parent=obs_phases.SPAN_RECOVER)
        self.last_recovery = {
            "restored_checkpoint": restored,
            "epochs_replayed": epochs,
            "records_replayed": replayed,
            "journal_tail": self.journal.tail_reason.value,
            "seconds": seconds,
            "compile_seconds": compile_seconds,
            "replay_seconds": replay_seconds,
            # real XLA compilations during recovery: with a persistent
            # cache active the cache-miss count is exact (retrievals
            # don't fire it); without one only the compile-or-retrieve
            # invocation count exists, and every one is a compile
            "compiled_programs": (
                compile_watch.cache_misses
                if self.compile_cache is not None
                and self.compile_cache.active
                else compile_watch.backend_compiles),
            "results": results,
        }
        log.info("recovery complete: %d epoch(s), %d journaled "
                 "chunk(s) replayed, %.3fs (tail: %s)", len(epochs),
                 replayed, seconds, self.journal.tail_reason.value)
        return self.last_recovery

    def last_schedule_info(self) -> tuple:
        """(commit version, elapsed seconds) of THE CALLING THREAD's
        most recent schedule() — race-free under the threaded sidecar,
        where the shared last_* attributes can reflect another
        connection's commit. Raises for a thread that never scheduled:
        a silent fallback to the shared attributes would reintroduce
        the exact misattribution this API exists to prevent."""
        version = getattr(self._tls, "version", None)
        if version is None:
            raise RuntimeError(
                "last_schedule_info: this thread has not called "
                "schedule(); read last_committed_version/last_elapsed "
                "for the shared (racy) values instead")
        return version, self._tls.elapsed

    def summary(self) -> dict:
        with self._counter_lock:
            batches, placed = self.batches, self.pods_placed
        return {
            "batches": batches,
            "podsPlaced": placed,
            "lastCycleSeconds": round(self.last_elapsed, 4),
            "cycleTimeouts": self.monitor.timeouts,
            "snapshotVersion": self.store.version,
            "degradationLevel": DegradationLadder.LEVELS[self.ladder.level],
            "ladderTransitions": len(self.ladder.transitions),
            "lastHealthWord": self.last_health_word,
            # deliberately lockless: a monitoring read must never queue
            # behind an in-flight device program on the commit lock;
            # torn values here cost a stale dashboard sample, nothing
            # more
            "meshSize": self._last_mesh_size,  # koordlint: disable=GB001
            "epoch": self.epoch,  # koordlint: disable=GB001
            "journaled": self.journal is not None,
        }

    def health(self) -> dict:
        """The koordcost health snapshot: the degradation rung, SLO
        status (burn rates, remaining budget) when an SloTracker is
        attached, device-memory telemetry + HBM headroom when a
        MemWatch is attached, and the journal's replay lag. `ok` is
        the one-bit verdict: every SLO objective inside budget AND the
        leak sentinel silent — a service built without either plane is
        vacuously ok (this method stays cheap and lock-free either
        way, like summary())."""
        slo_status = self.slo.status() if self.slo is not None else None
        mem = self.memwatch.snapshot() \
            if self.memwatch is not None else None
        ok = True
        budget_remaining = None
        if slo_status is not None:
            ok = slo_status["ok"]
            budget_remaining = slo_status["budget_remaining"]
        leak_events = 0 if mem is None else mem["leak_events"]
        return {
            "ok": bool(ok and leak_events == 0),
            "rung": DegradationLadder.LEVELS[self.ladder.level],
            "slo": slo_status,
            "budgetRemaining": budget_remaining,
            "memory": mem,
            "hbmHeadroomBytes":
                None if mem is None else mem["headroom_bytes"],
            "leakEvents": leak_events,
            # epochs still resident in the journal = how much a crash
            # right now would have to replay (pruned at checkpoints)
            "journalLagEpochs":
                len(self.journal.epochs())
                if self.journal is not None else 0,
            "lastCycleSeconds": round(self.last_elapsed, 4),
            "snapshotVersion": self.store.version,
        }
