"""frameworkext: the extender seam around the batched scheduling core —
cycle watchdog, live score introspection, plugin service endpoints, and the
sidecar-facing scheduler service.

Capability parity with pkg/scheduler/frameworkext (SURVEY.md 2.1):
- SchedulerMonitor (scheduler_monitor.go:40-52): records each batch's
  start; a completion past the timeout logs a warning and increments a
  counter; overdue in-flight cycles are queryable (the watchdog thread).
- Debug score tables (debug.go:42-59): when enabled, every scheduled batch
  dumps a pretty-printed top-N nodes-by-score table per pod — the direct
  fixture for eyeballing the TPU score matrix.
- Services (services/): every registered provider serves its summary at
  /apis/v1/plugins/<name> on a plain HTTP endpoint; /debug/flags/s toggles
  the score dump at runtime like the reference's DebugScoresSetter.
- SchedulerService: the seam the control-plane edge calls (the gRPC
  sidecar boundary per BASELINE.json): holds the SnapshotStore, schedules
  pod batches chunk-by-chunk against the current snapshot, publishes the
  post-commit snapshot, and reports through the monitor/debug hooks.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from koordinator_tpu.utils.httpserver import (
    BackgroundHTTPServer,
    QuietJsonHandler,
)

from koordinator_tpu.metrics import kernel_timer
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.snapshot.schema import ClusterSnapshot, PodBatch
from koordinator_tpu.snapshot.store import SnapshotStore

log = logging.getLogger(__name__)


class SchedulerMonitor:
    """Per-batch cycle watchdog."""

    def __init__(self, timeout_seconds: float = 30.0,
                 metrics: Optional[SchedulerMetrics] = None):
        self.timeout = timeout_seconds
        self.timeouts = 0
        self.metrics = metrics
        self._lock = threading.Lock()
        self._inflight: Dict[int, float] = {}
        self._seq = 0

    def start_cycle(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._seq += 1
            self._inflight[self._seq] = now
            return self._seq

    def complete_cycle(self, token: int,
                       now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            started = self._inflight.pop(token, now)
            elapsed = now - started
            if elapsed > self.timeout:
                # inside the lock: concurrent sidecar cycles would
                # otherwise lose timeout increments
                self.timeouts += 1
        if elapsed > self.timeout:
            if self.metrics is not None:
                self.metrics.scheduling_timeout.labels("default").inc()
            log.warning("scheduling cycle exceeded %.0fs: %.2fs",
                        self.timeout, elapsed)
        return elapsed

    def overdue(self, now: Optional[float] = None) -> List[int]:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [t for t, s in self._inflight.items()
                    if now - s > self.timeout]


def debug_score_table(snap: ClusterSnapshot, pods: PodBatch,
                      cfg: LoadAwareConfig, top_n: int = 5,
                      pod_names: Optional[List[str]] = None) -> str:
    """Top-N nodes by summed plugin score per pod (debug.go:61
    debugScores) recomputed from the snapshot with the same kernels the
    commit loop uses."""
    from koordinator_tpu.scheduler.plugins import (
        deviceshare,
        loadaware,
        numaaware,
    )

    scores = np.asarray(loadaware.score_matrix(snap.nodes, pods, cfg))
    scores = scores + np.asarray(numaaware.numa_score_matrix(
        snap.nodes, pods))
    if snap.devices.gpu_free.shape[1] > 0:
        scores = scores + np.asarray(
            deviceshare.score_matrix(snap.devices, pods))
    feasible = (np.asarray(loadaware.filter_mask(snap.nodes, pods, cfg))
                & np.asarray(snap.nodes.schedulable)[None, :])
    forbid, penalty = _taint_matrices(snap, pods)
    if forbid is not None:
        feasible &= ~forbid
        scores = np.maximum(scores - penalty, 0.0)
    scores = np.where(feasible, scores, -1.0)
    lines = []
    p = pods.num_pods
    for i in range(p):
        name = pod_names[i] if pod_names else f"pod[{i}]"
        order = np.argsort(-scores[i])[:top_n]
        cells = " | ".join(f"node{int(n)}:{scores[i, n]:.1f}"
                           for n in order if scores[i, n] >= 0)
        lines.append(f"{name:<24} | {cells}")
    header = f"{'pod':<24} | top-{top_n} nodes by score"
    return "\n".join([header, "-" * len(header)] + lines)


def _taint_matrices(snap: ClusterSnapshot, pods: PodBatch):
    """(forbid [P, N], penalty [P, N]) from the TaintToleration matrices,
    or (None, None) for a batch without taint modeling — the same math
    the batch kernel applies (core.py use_taints block)."""
    if not pods.has_taints:
        return None, None
    tid = np.maximum(np.asarray(pods.toleration_id), 0)
    tg = np.asarray(snap.nodes.taint_group)
    forbid = np.asarray(pods.tol_forbid)[tid][:, tg]
    prefer = np.asarray(pods.tol_prefer)[tid][:, tg]
    max_cnt = max(float(np.asarray(pods.tol_prefer).max()), 1.0)
    from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
    return forbid, prefer / max_cnt * MAX_NODE_SCORE


def debug_filter_table(snap: ClusterSnapshot, pods: PodBatch,
                       cfg: LoadAwareConfig,
                       pod_names: Optional[List[str]] = None) -> str:
    """Per-pod filter diagnosis (debug.go DebugFiltersSetter
    /debug/flags/f): how many nodes each gate rejects, recomputed from
    the snapshot with the same prefilter kernels the batch uses — the
    per-plugin failure breakdown the reference prints per pod."""
    from koordinator_tpu.scheduler.plugins import (
        deviceshare,
        loadaware,
        numaaware,
    )

    nodes = snap.nodes
    n = int(nodes.num_nodes)
    gates: List[tuple] = []
    gates.append(("Unschedulable",
                  np.broadcast_to(np.asarray(nodes.schedulable)[None, :],
                                  (pods.num_pods, n))))
    alloc = np.asarray(nodes.allocatable)
    req = np.asarray(pods.requests)
    fit = np.all(req[:, None, :] + np.asarray(nodes.requested)[None]
                 <= alloc[None] + 1e-3, axis=-1)
    gates.append(("NodeResourcesFit", fit))
    gates.append(("LoadAwareScheduling",
                  np.asarray(loadaware.filter_mask(nodes, pods, cfg))))
    forbid, _ = _taint_matrices(snap, pods)
    if forbid is not None:
        gates.append(("TaintToleration", ~forbid))
    if pods.has_spread:
        # carrier-matrix gating (multi-constraint pods) — mirrors core
        dom_all = np.asarray(pods.spread_domain)           # [Sg, N]
        counts = np.asarray(pods.spread_count0)
        dvalid = np.asarray(pods.spread_dvalid)
        skew = np.asarray(pods.spread_max_skew)
        soft = ~np.isfinite(skew)
        min_c = np.min(np.where(dvalid, counts, np.inf), axis=1)
        min_c = np.where(np.isfinite(min_c), min_c, 0.0)
        cnt_at = np.where(dom_all >= 0,
                          np.take_along_axis(counts,
                                             np.maximum(dom_all, 0),
                                             axis=1), 0.0)
        ok_map = soft[:, None] | ((dom_all >= 0)
                                  & (cnt_at + 1.0 - min_c[:, None]
                                     <= skew[:, None] + 1e-3))
        blocked = (np.asarray(pods.spread_carrier).astype(float)
                   @ (~ok_map).astype(float)) > 0.5
        gates.append(("PodTopologySpread", ~blocked))
    if pods.has_anti:
        # (a) per-group occupancy gated by the CARRIER matrix (a pod
        # carrying several terms is gated by each — mirrors core.py)
        dom_all = np.asarray(pods.anti_domain)
        occ_a = np.where(dom_all >= 0,
                         np.take_along_axis(
                             np.asarray(pods.anti_count0),
                             np.maximum(dom_all, 0), axis=1), 0.0) > 0.5
        blocked_a = (np.asarray(pods.anti_carrier).astype(float)
                     @ occ_a.astype(float)) > 0.5
        # direction (b): matching pods avoid carrier domains
        carr = np.asarray(pods.anti_carrier_count0)
        occ = np.where(dom_all >= 0,
                       np.take_along_axis(carr, np.maximum(dom_all, 0),
                                          axis=1), 0.0) > 0.5
        blocked = (np.asarray(pods.anti_member).astype(float)
                   @ occ.astype(float)) > 0.5
        gates.append(("InterPodAntiAffinity", ~blocked_a & ~blocked))
    if pods.has_aff:
        # carrier-matrix gating with per-(pod, group) bootstrap
        dom_all = np.asarray(pods.aff_domain)              # [Fg, N]
        counts = np.asarray(pods.aff_count0)
        carrier = np.asarray(pods.aff_carrier)
        member = np.asarray(pods.aff_member)
        total = counts.sum(axis=1)
        cc_map = np.where(dom_all >= 0,
                          np.take_along_axis(counts,
                                             np.maximum(dom_all, 0),
                                             axis=1), 0.0)
        boot_pg = carrier & member & (total < 0.5)[None, :]
        bad_nonboot = ((dom_all < 0) | (cc_map <= 0.5)).astype(float)
        bad_boot = (dom_all < 0).astype(float)
        blocked = ((carrier & ~boot_pg).astype(float) @ bad_nonboot
                   + boot_pg.astype(float) @ bad_boot) > 0.5
        gates.append(("InterPodAffinity", ~blocked))
    if np.asarray(nodes.numa_valid).any():
        gates.append(("NodeNUMAResource",
                      np.asarray(numaaware.zone_prefilter(nodes, pods))))
    if snap.devices.gpu_free.shape[1] > 0:
        gates.append(("DeviceShare",
                      np.asarray(deviceshare.prefilter(snap.devices,
                                                       pods))))
    lines = []
    for i in range(pods.num_pods):
        name = pod_names[i] if pod_names else f"pod[{i}]"
        feasible = np.ones((n,), bool)
        cells = []
        for gate_name, mask in gates:
            rejected = int((~mask[i] & feasible).sum())
            feasible &= mask[i]
            if rejected:
                cells.append(f"{gate_name}:-{rejected}")
        cells.append(f"fit:{int(feasible.sum())}/{n}")
        lines.append(f"{name:<24} | {' '.join(cells)}")
    header = f"{'pod':<24} | nodes rejected per gate"
    return "\n".join([header, "-" * len(header)] + lines)


class ServiceRegistry:
    """APIServiceProvider registry: name -> summary() (services.go:44-51)."""

    def __init__(self):
        self._providers: Dict[str, Callable[[], dict]] = {}

    def register(self, name: str, summary: Callable[[], dict]) -> None:
        self._providers[name] = summary

    def names(self) -> List[str]:
        return sorted(self._providers)

    def summary(self, name: str) -> Optional[dict]:
        fn = self._providers.get(name)
        return fn() if fn is not None else None


class DebugFlags:
    """Runtime debug toggles (debug.go DebugScoresSetter /debug/flags/s)."""

    def __init__(self):
        self.score_top_n = 0     # 0 = disabled
        self.filter_dump = False  # /debug/flags/f (DebugFiltersSetter)


class ServicesServer:
    """HTTP endpoint: /apis/v1/plugins/<name> summaries, /debug/flags/s,
    and Prometheus-format /metrics exposition."""

    def __init__(self, registry: ServiceRegistry, flags: DebugFlags,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics_registry=None):
        if metrics_registry is None:
            from koordinator_tpu.metrics import global_registry
            metrics_registry = global_registry()
        registry_ref, flags_ref = registry, flags
        metrics_ref = metrics_registry

        class Handler(QuietJsonHandler):
            def do_GET(self):
                if self.path == "/metrics":
                    self.reply_raw(200, "text/plain; version=0.0.4",
                                   metrics_ref.expose().encode())
                    return
                if self.path == "/apis/v1/plugins":
                    self.reply_json(200, {"plugins": registry_ref.names()})
                    return
                prefix = "/apis/v1/plugins/"
                if self.path.startswith(prefix):
                    summary = registry_ref.summary(self.path[len(prefix):])
                    if summary is None:
                        self.reply_json(404, {"error": "unknown plugin"})
                    else:
                        self.reply_json(200, summary)
                    return
                self.reply_json(404, {"error": "not found"})

            def do_PUT(self):
                if self.path.startswith("/debug/flags/s"):
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode().strip()
                    try:
                        flags_ref.score_top_n = int(raw or "0")
                    except ValueError:
                        self.reply_json(400, {"error": f"bad value {raw!r}"})
                        return
                    self.reply_json(200,
                                    {"scoreTopN": flags_ref.score_top_n})
                    return
                if self.path.startswith("/debug/flags/f"):
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode().strip().lower()
                    flags_ref.filter_dump = raw in ("1", "true", "on")
                    self.reply_json(200,
                                    {"filterDump": flags_ref.filter_dump})
                    return
                self.reply_json(404, {"error": "not found"})

        self._server = BackgroundHTTPServer(Handler, host, port)
        self.port = self._server.port

    def close(self) -> None:
        self._server.close()


class SchedulerService:
    """The sidecar seam: snapshot in, assignments out.

    The control-plane edge publishes snapshots (or functional deltas) into
    the store and feeds pending-pod batches; each batch runs the full
    device program, the post-commit snapshot becomes the next version, and
    the per-cycle watchdog + optional score dump observe every batch.
    """

    def __init__(self, store: Optional[SnapshotStore] = None,
                 cfg: Optional[LoadAwareConfig] = None,
                 monitor: Optional[SchedulerMonitor] = None,
                 flags: Optional[DebugFlags] = None,
                 registry: Optional[ServiceRegistry] = None,
                 metrics: Optional[SchedulerMetrics] = None,
                 **schedule_kwargs):
        self.store = store or SnapshotStore()
        self.cfg = cfg if cfg is not None else LoadAwareConfig.make()
        self.metrics = metrics if metrics is not None else SchedulerMetrics()
        self.monitor = monitor or SchedulerMonitor(metrics=self.metrics)
        if self.monitor.metrics is None:
            self.monitor.metrics = self.metrics
        self.flags = flags or DebugFlags()
        self.registry = registry or ServiceRegistry()
        # auto_pack: derive the batching-layer specializations the bench
        # uses — domain classes for same-topologyKey groups, and the
        # topo/numa/gpu prefix packing contracts — per batch, invisibly
        # to callers (results come back in the caller's pod order).
        # Prefix widths are bucketed to powers of two so steady-state
        # traffic compiles a handful of program variants, not one per
        # constrained-count.
        self.auto_pack = bool(schedule_kwargs.pop("auto_pack", True))
        self.schedule_kwargs = schedule_kwargs
        self._explicit_amp = "enable_amplification" in schedule_kwargs
        self.batches = 0
        self.pods_placed = 0
        self.last_elapsed = 0.0
        # snapshot ingest and batch commits are serialized: a publish
        # landing mid-batch would otherwise be silently replaced by the
        # post-commit snapshot derived from the PREVIOUS version
        self._commit_lock = threading.Lock()
        # pre->default->post error chain; plugins (reservation writeback)
        # register filters (errorhandler_dispatcher.go)
        from koordinator_tpu.scheduler.errorhandler import (
            ErrorHandlerDispatcher,
        )
        self.error_dispatcher = ErrorHandlerDispatcher()
        # version of the last commit THIS service made (read under the
        # commit lock; `store.version` alone can already reflect another
        # thread's later commit)
        self.last_committed_version = 0
        # per-thread (version, elapsed) of the calling thread's last
        # schedule() — see last_schedule_info
        self._tls = threading.local()
        self._counter_lock = threading.Lock()
        # called with (failed_gang_indices, result) when a batch PROVES
        # strict gangs short of quorum; the gang controller un-assumes
        # their held members through store.forget with the batches it
        # retained (the immediate tier of the Permit rollback — the
        # wait-expiry timeout stays the backstop for gangs whose members
        # simply never reappear)
        self.on_gang_failed: Optional[Callable] = None
        self.last_gang_failed: Optional[np.ndarray] = None
        # called with (assignment, typed_pods, result) after each commit
        # when typed_pods was provided: the host assume-cache hook
        # (SnapshotSyncer.attach_scheduler) records placed pods so
        # rebuilds/topology deltas keep the in-flight charges
        self.on_assumed: Optional[Callable] = None
        self.registry.register("scheduler", self.summary)

    def commit_guard(self):
        """The batch-commit lock, exposed so host-side snapshot writers
        (SnapshotSyncer) can serialize rebuild/ingest publishes with
        in-flight schedule commits: an unserialized rebuild landing
        between a batch's snapshot read and its post-commit publish
        would be silently overwritten (lost update), and the assume
        hook would resolve result rows against a swapped builder.
        Lock order is commit -> view, everywhere."""
        return self._commit_lock

    def publish(self, snapshot: ClusterSnapshot) -> int:
        """Returns the published version, read under the commit lock so a
        concurrent mutator cannot be misattributed."""
        with self._commit_lock:
            self.store.publish(snapshot)
            self.last_committed_version = self.store.version
            return self.last_committed_version

    def ingest(self, delta) -> int:
        """Apply an O(K) metric delta SERIALIZED with batch commits — a
        delta landing between a batch's snapshot read and its post-commit
        publish would be silently overwritten (the same hazard the commit
        lock exists for; see the lock comment above)."""
        with self._commit_lock:
            self.store.ingest(delta)
            self.last_committed_version = self.store.version
            return self.last_committed_version

    # batches at or below this size schedule as-is: the quadratic
    # [P, P] savings cannot pay for the pack/unpack permutations there
    AUTO_PACK_MIN_BATCH = 512

    def _prepare_batch(self, snap: ClusterSnapshot, pods: PodBatch):
        """Derive the batching-layer specializations for this batch:
        `(maybe-packed pods, extra static kwargs, inverse permutation
        or None)`. Every contract the kwargs claim is established or
        verified here, host-side (the scheduler silently trusts them):
        domain classes come from actual row equality, prefixes from an
        actual pack, and numa_prefix only on a policy-free snapshot."""
        from koordinator_tpu.utils import synthetic as batching

        from koordinator_tpu.scheduler.plugins import deviceshare

        kwargs = {}
        if not self.auto_pack:
            return pods, kwargs, None
        if pods.has_spread or pods.has_anti or pods.has_aff:
            classes = batching.dom_classes(pods)
            if any(len(c) > 1 for fam in classes for c in fam):
                # all-singleton partitions ARE the default program —
                # omitting them avoids a needless static-arg variant.
                # NOTE: a CHANGING partition across batches is a
                # recompile trigger (dom_classes is a static jit arg);
                # group structure is stable in steady-state traffic,
                # and auto_pack=False opts out entirely.
                kwargs["dom_classes"] = classes
        p = int(np.asarray(pods.valid).shape[0])
        if p <= self.AUTO_PACK_MIN_BATCH:
            return pods, kwargs, None

        # cheap masks FIRST; the full batch copy + contract validation
        # in pack_gate_prefixes runs only when a prefix survives
        topo = batching.topo_constrained_mask(pods)
        numa = np.asarray(pods.numa_single, bool)
        gpu = np.asarray(deviceshare.has_device_request(pods), bool)

        def bucket(count):
            # power-of-two widths (>= the packer's tight 128-aligned
            # prefix by construction) bound the compile variants; a
            # class covering most of the batch is not worth a prefix
            if count == 0 or count >= p // 2:
                return None
            width = 128
            while width < count:
                width *= 2
            return min(width, p)

        want = {}
        if topo.any():
            want["topo_prefix"] = bucket(int(topo.sum()))
        if self.schedule_kwargs.get("enable_numa", True) and numa.any() \
                and not np.asarray(snap.nodes.numa_policy).any():
            want["numa_prefix"] = bucket(int((topo | numa).sum()))
        if self.schedule_kwargs.get("enable_devices", True) \
                and gpu.any():
            want["gpu_prefix"] = bucket(int((topo | numa | gpu).sum()))
        want = {k: v for k, v in want.items() if v is not None}
        if not want:
            return pods, kwargs, None  # classes alone need no reorder
        packed, _, masks = batching.pack_gate_prefixes(pods, p)
        kwargs.update(want)
        perm = masks["perm"]
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.size)
        return packed, kwargs, inv

    def schedule(self, pods: PodBatch,
                 pod_names: Optional[List[str]] = None,
                 typed_pods: Optional[List] = None) -> core.ScheduleResult:
        """`typed_pods` (batch-ordered api.Pod list) opts unplaced rows
        into the error-handler chain — the reservation filter needs the
        typed pod to recognize reserve pods."""
        token = self.monitor.start_cycle()
        with self._commit_lock:
            snap = self.store.current()
            # amplified-CPU auto-detection happens on the snapshot the
            # batch actually runs against (an explicit
            # enable_amplification kwarg from the constructor wins).
            # Deriving here rather than at publish time keeps the flag
            # correct for writers that bypass service.publish() and put
            # snapshots straight into the shared SnapshotStore
            # (SnapshotSyncer._rebuild, embedded compositions).
            if not self._explicit_amp:
                self.schedule_kwargs["enable_amplification"] = bool(
                    np.asarray(snap.nodes.cpu_amplification > 1.0).any())
            sched_pods, pack_kwargs, inv = self._prepare_batch(snap, pods)
            with kernel_timer(self.metrics.kernel_seconds,
                              "koord/schedule_batch"):
                result = core.schedule_batch(
                    snap, sched_pods, self.cfg,
                    **{**self.schedule_kwargs, **pack_kwargs})
                if inv is not None:
                    # back to the CALLER's pod order before anything
                    # (hooks, error chain, debug tables) sees the result
                    result = result.replace(
                        **{f: getattr(result, f)[inv]
                           for f in core.PER_POD_RESULT_FIELDS})
                # single D2H transfer doubles as the completion barrier
                # (and makes the kernel timer measure device time)
                assignment = np.asarray(result.assignment)
            self.store.update(lambda _old: result.snapshot)
            # THIS call's commit version, captured under the lock — the
            # shared last_committed_version attribute can already
            # reflect a racing ingest by the time a caller reads it
            version = self.store.version
            self.last_committed_version = version
            if self.on_assumed is not None and typed_pods is not None:
                # under the commit lock: an attached syncer's rebuild
                # (which serializes on the same lock) cannot swap the
                # builder between this batch's snapshot and the hook's
                # row-name resolution
                self.on_assumed(assignment, typed_pods, result)
        self.last_elapsed = elapsed = self.monitor.complete_cycle(token)
        # per-CALL (version, elapsed) for the calling thread: the
        # threaded sidecar reads them after scheduling, and the shared
        # attributes race with concurrent ingests/schedules
        self._tls.version = version
        self._tls.elapsed = elapsed
        self.metrics.cycle_seconds.observe(elapsed)
        valid = np.asarray(pods.valid)
        placed_n = int(((assignment >= 0) & valid).sum())
        with self._counter_lock:
            # += on the shared counters is not atomic across threads;
            # the threaded sidecar schedules concurrently
            self.batches += 1
            self.pods_placed += placed_n
        self.metrics.pods_scheduled.labels("placed").inc(placed_n)
        self.metrics.pods_scheduled.labels("unschedulable").inc(
            int(((assignment < 0) & valid).sum()))
        self.metrics.snapshot_version.set(float(self.store.version))
        gang_failed = np.asarray(result.gang_failed)
        self.last_gang_failed = gang_failed
        if gang_failed.any() and self.on_gang_failed is not None:
            self.on_gang_failed(np.where(gang_failed)[0], result)
        if typed_pods is not None:
            from koordinator_tpu.scheduler.errorhandler import (
                dispatch_batch_errors,
            )
            dispatch_batch_errors(self.error_dispatcher, assignment, valid,
                                  typed_pods)
        if self.flags.score_top_n > 0:
            log.info("score table:\n%s", debug_score_table(
                snap, pods, self.cfg, self.flags.score_top_n, pod_names))
        if self.flags.filter_dump:
            log.info("filter table:\n%s", debug_filter_table(
                snap, pods, self.cfg, pod_names))
        return result

    def last_schedule_info(self) -> tuple:
        """(commit version, elapsed seconds) of THE CALLING THREAD's
        most recent schedule() — race-free under the threaded sidecar,
        where the shared last_* attributes can reflect another
        connection's commit. Raises for a thread that never scheduled:
        a silent fallback to the shared attributes would reintroduce
        the exact misattribution this API exists to prevent."""
        version = getattr(self._tls, "version", None)
        if version is None:
            raise RuntimeError(
                "last_schedule_info: this thread has not called "
                "schedule(); read last_committed_version/last_elapsed "
                "for the shared (racy) values instead")
        return version, self._tls.elapsed

    def summary(self) -> dict:
        return {
            "batches": self.batches,
            "podsPlaced": self.pods_placed,
            "lastCycleSeconds": round(self.last_elapsed, 4),
            "cycleTimeouts": self.monitor.timeouts,
            "snapshotVersion": self.store.version,
        }
