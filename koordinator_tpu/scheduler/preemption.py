"""Default priority preemption (the vanilla PostFilter the reference
inherits from upstream kube-scheduler, complementing the quota-scoped
preemption in plugins/quota_revoke.py — whose victim selection wraps the
shared reprieve helper here).

When a pod is unschedulable, dry-run every node the preemptor could
actually schedule onto (nodeSelector/affinity/toleration recheck — the
upstream reruns Filter after hypothetically removing victims, so a
nominated node must never be one the next batch's gates will reject):
lower-priority pods are removed hypothetically, the preemptor's fit is
rechecked, and reprieve adds candidates back from the most important
down, keeping as victims only those whose return breaks the fit (the
minimal-set shape of upstream selectVictimsOnNode). Among nodes where
preemption helps, pickOneNodeForPreemption's ordering applies: lowest
highest-victim priority, then lowest priority sum, then fewest victims.

Host-side by design: preemption is the cold path (it runs only for pods
the device program could not place), operates on the typed host view,
and its output — victims to evict + the nominated node — feeds the
eviction edge and the NEXT batch, exactly like the reference's
nominatedNodeName handshake.

Recheck coverage: the dry-run re-applies the node-level gates, the
flat resource fit WITH amplified-CPU charging (cpu-bind pods cost
request x the node's amplification ratio, matching the device gate in
core.py), the topology gates (spread/affinity), the single-NUMA zone
fit for CPU-bind preemptors (zone_admits — zone charges stay raw, the
ratio cancels), and, when the caller provides the Device CRs, the
per-instance GPU and aux (RDMA/FPGA) fit against surviving grants
plus the zone/instance AGREEMENT for bind+GPU preemptors
(fine_grained_admits — a best-effort mirror of the topology-manager
hint merge, truncated to the builder's zone capacity). Remaining
narrowings: with no `devices` mapping the per-instance gates are
skipped (aggregate capacity is still checked via the flat vector),
and exotic merged-hint policies are not reproduced — either way a
rejected nomination requeues (the reference's nominatedNodeName is
equally advisory and re-filtered at retry).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.scheduler.batching import EPS
from koordinator_tpu.snapshot.builder import resource_vec

ANNOTATION_PREEMPTIBLE = "scheduling.koordinator.sh/preemptible"


@dataclasses.dataclass
class NominatedPreemption:
    node_name: str
    victims: List[api.Pod]


def fits(used: np.ndarray, capacity: np.ndarray) -> bool:
    """Shared fit tolerance — the same EPS the device kernels use, so
    host preemption and the device program agree on boundary fits."""
    return bool((used <= capacity + EPS).all())


def preemptible(p: api.Pod) -> bool:
    return p.meta.annotations.get(ANNOTATION_PREEMPTIBLE) != "false"


def reprieve_victims(preemptor_req: np.ndarray,
                     candidates: Sequence[api.Pod],
                     extra_fit: Callable[[np.ndarray, List[api.Pod]],
                                         bool],
                     req_fn: Optional[Callable[[api.Pod],
                                               np.ndarray]] = None
                     ) -> Optional[List[api.Pod]]:
    """The remove-all-then-reprieve minimal-set core shared by default
    and quota-scoped preemption. `extra_fit(returned, reprieved)` must
    hold with `returned` = the summed requests of the reprieved
    candidates and `reprieved` their identities (so callers can re-run
    non-resource gates per reprieve step — upstream reruns the Filter
    plugins inside selectVictimsOnNode, which is what lets a pod blocked
    by anti-affinity against a PREEMPTIBLE pod evict it even when
    resources alone would fit). `req_fn` maps a candidate to its CHARGED
    request vector (defaults to raw requests; callers pass an amplifying
    variant on amplified nodes)."""
    if req_fn is None:
        req_fn = lambda p: resource_vec(p.requests).astype(np.float64)
    if not candidates:
        return None
    if not extra_fit(np.zeros_like(preemptor_req), []):
        return None  # even evicting every candidate is not enough
    victims: List[api.Pod] = []
    kept = np.zeros_like(preemptor_req)
    reprieved: List[api.Pod] = []
    for p in sorted(candidates, key=lambda p: -(p.priority or 0)):
        p_req = req_fn(p)
        if extra_fit(kept + p_req, reprieved + [p]):
            kept += p_req
            reprieved.append(p)
        else:
            victims.append(p)
    return victims or None


def effective_allocatable(node: api.Node,
                          device: Optional[api.Device]) -> np.ndarray:
    """Node allocatable with aggregate device capacity merged — the
    typed twin of builder._merge_device_allocatable: the device plugin
    reports GPU/aux extended resources unless the Node already did.
    Without this merge the flat preemption fit would reject EVERY
    device-requesting preemptor (capacity 0 in the Node CR)."""
    from koordinator_tpu.api.extension import ResourceKind as RK

    v = resource_vec(node.allocatable).astype(np.float64)
    if device is None:
        return v
    gc, gm = int(RK.GPU_CORE), int(RK.GPU_MEMORY)
    gpus = [d for d in device.devices if d.type == "gpu" and d.health]
    if gpus:
        if v[gc] == 0:
            # core is 100% per instance BY DEFINITION (the builder's
            # gpu_total row hardcodes (100, mem, 100) — GPU_CORE in the
            # CR's resources is ignored there and must be here too)
            v[gc] = 100.0 * len(gpus)
        if v[gm] == 0:
            v[gm] = sum(float(d.resources.get(RK.GPU_MEMORY, 0.0))
                        for d in gpus)
    for kind, typ in ((RK.RDMA, "rdma"), (RK.FPGA, "fpga")):
        kk = int(kind)
        if v[kk] == 0:
            v[kk] = sum(float(d.resources.get(kind, 100.0))
                        for d in device.devices
                        if d.type == typ and d.health)
    return v


def node_admits(pod: api.Pod, node: api.Node) -> bool:
    """The pod-level gates the device program will re-apply next batch:
    schedulable, nodeSelector, nodeAffinity expressions, tolerations."""
    if node.unschedulable:
        return False
    labels = node.meta.labels
    if not all(labels.get(k) == v for k, v in pod.node_selector.items()):
        return False
    if not all(r.matches(labels) for r in pod.node_affinity):
        return False
    for taint in node.taints:
        if taint.effect in ("NoSchedule", "NoExecute") and not any(
                t.tolerates(taint) for t in pod.tolerations):
            return False
    return True


def charged_request(p: api.Pod, cpu_amplification: float) -> np.ndarray:
    """What the pod costs against (amplified) node allocatable — the
    host twin of the device gate (core.py amplified-CPU commit): a
    CPU-bind (exclusive-cpuset) pod's cores cost request x ratio on a
    node whose webhook published amplified allocatable; shared-CPU pods
    charge raw."""
    v = resource_vec(p.requests).astype(np.float64)
    if cpu_amplification > 1.0 and p.required_cpu_bind:
        from koordinator_tpu.api.extension import ResourceKind
        v[int(ResourceKind.CPU)] *= cpu_amplification
    return v


def select_victims_on_node(preemptor: api.Pod,
                           node_allocatable: np.ndarray,
                           pods_on_node: Sequence[api.Pod],
                           admit: Optional[Callable] = None,
                           cpu_amplification: float = 1.0,
                           fine_fit: Optional[Callable] = None
                           ) -> Optional[List[api.Pod]]:
    """Minimal victim set on one node, or None when preemption there
    cannot admit the preemptor. `admit(removed_ids)` re-runs the
    non-resource gates with that candidate subset hypothetically
    evicted (None = resources only). `cpu_amplification` is the node's
    published ratio: bind-pod CPU charges amplified, matching what the
    device gates will re-check next batch. `fine_fit(survivors)`
    re-runs the fine-grained gates (NUMA zone / GPU instances) against
    the surviving pod set per reprieve step."""
    prio = preemptor.priority or 0

    def is_candidate(p: api.Pod) -> bool:
        return (p.priority or 0) < prio and preemptible(p)

    def req_of(p: api.Pod) -> np.ndarray:
        return charged_request(p, cpu_amplification)

    candidates = [p for p in pods_on_node if is_candidate(p)]
    others = [p for p in pods_on_node if not is_candidate(p)]
    req = req_of(preemptor)
    base = sum((req_of(p) for p in others), np.zeros_like(req))
    cap = node_allocatable.astype(np.float64)
    cand_ids = {id(p) for p in candidates}

    def extra_fit(returned: np.ndarray,
                  reprieved: List[api.Pod]) -> bool:
        if not fits(base + returned + req, cap):
            return False
        if fine_fit is not None and not fine_fit(others + reprieved):
            return False
        if admit is None:
            return True
        removed = frozenset(cand_ids - {id(p) for p in reprieved})
        return admit(removed)

    return reprieve_victims(req, candidates, extra_fit, req_fn=req_of)


# the snapshot builder truncates zones to its max_zones capacity
# (_fill_identity_row zones[:z]); the dry run must never count a zone
# the device gate cannot model
DEFAULT_MAX_ZONES = 4


def _zone_fit_list(preemptor: api.Pod, node: api.Node,
                   survivors: Sequence[api.Pod],
                   max_zones: int) -> Optional[List[bool]]:
    """Per-zone cpu/mem fit for a CPU-bind preemptor against the
    SURVIVING bound pods' zone usage, over the zones the snapshot
    actually models. None = no zone gate applies (non-bind preemptor);
    [] = bind preemptor on a zone-less node (never admissible). Zone
    charges stay RAW: zone capacities are raw and the amplification
    ratio cancels in the fit (core.py amplified-CPU note)."""
    from koordinator_tpu.api.extension import ResourceKind as RK

    if not preemptor.required_cpu_bind:
        return None
    if node.topology is None or not node.topology.zones:
        return []
    zones = node.topology.zones[:max_zones]
    req_cpu = float(preemptor.requests.get(RK.CPU, 0.0))
    req_mem = float(preemptor.requests.get(RK.MEMORY, 0.0))
    used = [[0.0, 0.0] for _ in zones]
    for p in survivors:
        zi = p.allocated_numa_zone
        if p.required_cpu_bind and 0 <= zi < len(zones):
            used[zi][0] += float(p.requests.get(RK.CPU, 0.0))
            used[zi][1] += float(p.requests.get(RK.MEMORY, 0.0))
    return [z.cpus_milli - u[0] + EPS >= req_cpu
            and z.memory_mib - u[1] + EPS >= req_mem
            for z, u in zip(zones, used)]


def zone_admits(preemptor: api.Pod, node: api.Node,
                survivors: Sequence[api.Pod],
                max_zones: int = DEFAULT_MAX_ZONES) -> bool:
    """Single-NUMA fit for a CPU-bind preemptor — the numa_single gate
    the next batch re-runs (numaaware.zone_prefilter + the exact commit
    gate). Non-bind preemptors pass; bind preemptors on zone-less nodes
    never do (the gate's numa_valid is all-False there)."""
    fit = _zone_fit_list(preemptor, node, survivors, max_zones)
    return True if fit is None else any(fit)


def device_admits(preemptor: api.Pod, device: Optional[api.Device],
                  survivors: Sequence[api.Pod]) -> bool:
    """Per-instance GPU and aux (RDMA/FPGA) fit against the surviving
    pods' grants (the deviceshare instance gates the next batch
    re-runs). `device` is the node's Device CR; a device-requesting
    preemptor on a device-less node never fits. Pass-through for
    preemptors requesting no device."""
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.snapshot.builder import gpu_per_instance_host

    if not wants_device(preemptor):
        return True
    if device is None:
        return False
    if wants_gpu(preemptor):
        free, _, total_mem = _gpu_free_map(device, survivors)
        count, per = gpu_per_instance_host(total_mem, preemptor)
        if count > 0 and sum(1 for f in free.values()
                             if (f + EPS >= per).all()) < count:
            return False
    # aux pools: one instance must hold the WHOLE request
    # (deviceshare's desiredCount-1 semantics)
    for typ, inst_attr, kind in (("rdma", "allocated_rdma_inst",
                                  RK.RDMA),
                                 ("fpga", "allocated_fpga_inst",
                                  RK.FPGA)):
        a_req = float(preemptor.requests.get(kind, 0.0))
        if a_req <= 0:
            continue
        free_aux = {info.minor: float(info.resources.get(kind, 100.0))
                    for info in device.devices
                    if info.type == typ and info.health}
        for p in survivors:
            p_req = float(p.requests.get(kind, 0.0))
            inst = getattr(p, inst_attr)
            if p_req > 0 and inst in free_aux:
                free_aux[inst] = max(free_aux[inst] - p_req, 0.0)
        if not any(f + EPS >= a_req for f in free_aux.values()):
            return False
    return True


def _gpu_free_map(device: api.Device, survivors: Sequence[api.Pod]):
    """(per-minor free [core, mem, ratio] after surviving grants,
    minor -> numa node, per-instance total memory)."""
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.snapshot.builder import gpu_per_instance_host

    free, numa, total_mem = {}, {}, 0.0
    for info in device.devices:
        if info.type == "gpu" and info.health:
            total_mem = float(info.resources.get(RK.GPU_MEMORY, 0.0))
            free[info.minor] = np.array([100.0, total_mem, 100.0])
            numa[info.minor] = info.numa_node
    for p in survivors:
        if p.allocated_gpu_minors:
            _, per = gpu_per_instance_host(total_mem, p)
            for m in p.allocated_gpu_minors:
                if m in free:
                    free[m] = np.maximum(free[m] - per, 0.0)
    return free, numa, total_mem


def fine_grained_admits(preemptor: api.Pod, node: api.Node,
                        device: Optional[api.Device],
                        survivors: Sequence[api.Pod],
                        devices_known: bool,
                        max_zones: int = DEFAULT_MAX_ZONES) -> bool:
    """Best-effort host mirror of the fine-grained gates the next batch
    re-runs: single-NUMA zone fit, per-instance GPU/aux fit, and — for
    a bind+GPU preemptor — their AGREEMENT on one zone (the topology-
    manager hint merge: the zone that holds the cpus must also hold
    enough free instances; instances with unknown NUMA (-1) count
    toward every zone). The EXACT merged-hint policy semantics live in
    scheduler/topologymanager.py; residual divergence is advisory-only
    (a rejected nomination requeues, like the reference's
    nominatedNodeName)."""
    from koordinator_tpu.snapshot.builder import gpu_per_instance_host

    zone_fit = _zone_fit_list(preemptor, node, survivors, max_zones)
    if zone_fit is not None and not any(zone_fit):
        return False
    if not devices_known:
        return True
    if not device_admits(preemptor, device, survivors):
        return False
    if zone_fit and device is not None and wants_gpu(preemptor):
        free, numa, total_mem = _gpu_free_map(device, survivors)
        count, per = gpu_per_instance_host(total_mem, preemptor)
        if count > 0:
            def zone_holds(z: int) -> bool:
                return sum(1 for m, f in free.items()
                           if numa.get(m, -1) in (z, -1)
                           and (f + EPS >= per).all()) >= count

            if not any(ok and zone_holds(z)
                       for z, ok in enumerate(zone_fit)):
                return False
    return True


def wants_gpu(pod: api.Pod) -> bool:
    from koordinator_tpu.api.extension import ResourceKind as RK
    return (float(pod.requests.get(RK.GPU_CORE, 0.0)) > 0
            or float(pod.requests.get(RK.GPU_MEMORY, 0.0)) > 0
            or pod.gpu_memory_ratio > 0)


def wants_device(pod: api.Pod) -> bool:
    """THE one predicate for 'this pod needs the per-instance device
    recheck' — shared by find_preemption's gating and device_admits."""
    from koordinator_tpu.api.extension import ResourceKind as RK
    return (wants_gpu(pod)
            or float(pod.requests.get(RK.RDMA, 0.0)) > 0
            or float(pod.requests.get(RK.FPGA, 0.0)) > 0)


def node_cpu_amplification(node: api.Node) -> float:
    """The node's published CPU amplification ratio — the shared parser
    in api/extension, so the snapshot builder and this dry run agree."""
    from koordinator_tpu.api.extension import (
        node_cpu_amplification_ratio,
    )
    return node_cpu_amplification_ratio(node.meta.annotations)


def _pod_matches(p: api.Pod, ns: str, selector) -> bool:
    return (p.meta.namespace == ns
            and all(p.meta.labels.get(k) == v
                    for k, v in selector.items()))


def constraints_admit(pod: api.Pod, node: api.Node,
                      nodes: Sequence[api.Node],
                      pods_by_node: Dict[str, Sequence[api.Pod]],
                      removed_ids: frozenset,
                      placed: Optional[List[tuple]] = None) -> bool:
    """The topology gates the device program re-applies next batch —
    required (anti-)affinity in both directions and hard spread —
    evaluated against the SURVIVING cluster view (victims removed). A
    nomination that fails any of these would cost victims their lives
    for a node the preemptor still cannot take. `placed` is the
    pre-materialized [(node, pod)] view (hoisted by find_preemption so
    repeated admission checks don't rebuild it)."""
    labels = node.meta.labels
    if placed is None:
        node_of = {n.meta.name: n for n in nodes}
        placed = [(node_of[n_name], p)
                  for n_name, plist in pods_by_node.items()
                  if n_name in node_of for p in plist]

    def survivors():
        for other, p in placed:
            if id(p) not in removed_ids:
                yield other, p

    ns = pod.meta.namespace
    for term in pod.pod_affinity:
        dom = labels.get(term.topology_key)
        if term.anti:
            if dom is None:
                continue  # keyless nodes pass (no pair can exist)
            for other, p in survivors():
                if (other.meta.labels.get(term.topology_key) == dom
                        and _pod_matches(p, ns, term.label_selector)):
                    return False
        else:
            if dom is None:
                return False
            total = 0
            here = False
            for other, p in survivors():
                if _pod_matches(p, ns, term.label_selector):
                    total += 1
                    if other.meta.labels.get(term.topology_key) == dom:
                        here = True
            if not here and not (
                    total == 0
                    and _pod_matches(pod, ns, term.label_selector)):
                return False
    # direction (b): surviving carriers' anti terms against the pod
    for other, p in survivors():
        for term in p.pod_affinity:
            if not term.anti:
                continue
            if not _pod_matches(pod, p.meta.namespace,
                                term.label_selector):
                continue
            cd = other.meta.labels.get(term.topology_key)
            if cd is not None and labels.get(term.topology_key) == cd:
                return False
    for c in pod.spread_constraints:
        if c.when_unsatisfiable != "DoNotSchedule":
            continue
        dom = labels.get(c.topology_key)
        if dom is None:
            return False
        counts: Dict[str, int] = {}
        eligible = set()
        for n in nodes:
            d = n.meta.labels.get(c.topology_key)
            if d is None:
                continue
            counts.setdefault(d, 0)
            if (all(n.meta.labels.get(k) == v
                    for k, v in pod.node_selector.items())
                    and all(r.matches(n.meta.labels)
                            for r in pod.node_affinity)):
                eligible.add(d)
        for other, p in survivors():
            d = other.meta.labels.get(c.topology_key)
            if d is not None and _pod_matches(p, ns, c.label_selector):
                counts[d] = counts.get(d, 0) + 1
        min_c = min((counts.get(d, 0) for d in eligible), default=0)
        if counts.get(dom, 0) + 1 - min_c > c.max_skew:
            return False
    return True


def find_preemption(preemptor: api.Pod,
                    nodes: Sequence[api.Node],
                    pods_by_node: Dict[str, Sequence[api.Pod]],
                    devices: Optional[Dict[str, api.Device]] = None
                    ) -> Optional[NominatedPreemption]:
    """Dry-run every ADMISSIBLE node; pick per pickOneNodeForPreemption
    ordering. Admissibility covers the node-level gates up front, the
    topology gates (spread/affinity), the NUMA-zone fit for bind
    preemptors, and — when `devices` maps node name -> Device CR — the
    per-instance GPU fit, all against the post-eviction view."""
    best: Optional[NominatedPreemption] = None
    best_key = None
    node_of = {n.meta.name: n for n in nodes}
    placed = [(node_of[n_name], p)
              for n_name, plist in pods_by_node.items()
              if n_name in node_of for p in plist]
    has_topology = bool(preemptor.pod_affinity
                        or preemptor.spread_constraints
                        or any(t.anti for _, p in placed
                               for t in p.pod_affinity))
    needs_fine = preemptor.required_cpu_bind or (
        devices is not None and wants_device(preemptor))
    for node in nodes:
        if not node_admits(preemptor, node):
            continue
        admit = None
        if has_topology:
            def admit(removed_ids, _node=node):
                return constraints_admit(preemptor, _node, nodes,
                                         pods_by_node, removed_ids,
                                         placed=placed)
        dev = devices.get(node.meta.name) if devices else None
        fine = None
        if needs_fine:
            def fine(survivors, _node=node, _dev=dev):
                return fine_grained_admits(preemptor, _node, _dev,
                                           survivors,
                                           devices_known=devices
                                           is not None)
        victims = select_victims_on_node(
            preemptor, effective_allocatable(node, dev),
            pods_by_node.get(node.meta.name, ()), admit=admit,
            cpu_amplification=node_cpu_amplification(node),
            fine_fit=fine)
        if victims is None:
            continue
        prios = sorted((p.priority or 0) for p in victims)
        key = (max(prios), sum(prios), len(victims))
        if best_key is None or key < best_key:
            best_key = key
            best = NominatedPreemption(node_name=node.meta.name,
                                       victims=victims)
    return best
