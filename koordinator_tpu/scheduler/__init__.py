"""The TPU-native scheduler: batched Filter/Score/Commit over the snapshot.

The reference's per-pod scheduling cycle (SURVEY.md 3.1) — PreFilter →
Filter (parallel over nodes) → Score → selectHost → Reserve → Permit →
PreBind → Bind — becomes one jitted program over a pods x nodes matrix:

- plugins (`plugins/`) are pure functions (snapshot, pod_batch) -> masks /
  score matrices, replacing the per-node Go loops (HOT LOOP #1/#2,
  framework_extender.go:204-259);
- `core.schedule_batch` fuses feasibility + scoring + a conflict-resolving
  batched commit (the assume/bind dance) in fixed rounds of lax.scan.
"""
