"""Scheduler sidecar: the RPC edge in front of SchedulerService.

The BASELINE north-star architecture (SURVEY.md §7 step 10): the
control-plane process (the reference's Go koord-scheduler) keeps its
informers/queues and calls this sidecar for the device part — publish
snapshot / ingest metric delta / schedule batch. Transport is the same
framed unix-socket RPC the runtime proxy uses (runtimeproxy/rpc.py);
array payloads are flax msgpack state dicts (language-neutral:
dtype+shape-tagged, readable from Go with any msgpack library).

Deserialization targets: flax `from_bytes` replaces leaves wholesale,
so a capacity-1 `zeros_snapshot()` template restores a snapshot of ANY
static shape — the wire needs no shape negotiation.

Cost model (measured on one v5e chip): a FULL 10k-node snapshot publish
is ~10 s on the wire — needed when capacity grows, when churn exceeds
one delta's row pad, or when a churned node hosts an Available
reservation (topology rows cannot carry reservation holds; see
snapshot/delta.py + builder.topology_delta). All other node add/
remove/update rides `ingest_topology` (O(K) rows, like the metric
deltas), so the steady state is O(K) deltas plus ~0.14 s RPC overhead
per 2k-pod schedule call, against ~0.15 s device time for the batch
itself.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence

import flax.serialization
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.runtimeproxy.rpc import RpcClient, RpcServer
from koordinator_tpu.scheduler import sidecar_pb2 as pb
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.snapshot.delta import NodeMetricDelta, NodeTopologyDelta
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    PodBatch,
    zeros_snapshot,
)


def _snapshot_template() -> ClusterSnapshot:
    # nested structure must match; leaf shapes are irrelevant
    return zeros_snapshot(num_nodes=1)


def _flat_template(cls):
    """Restore target for a FLAT flax struct: array dummies for pytree
    leaves only — STATIC (pytree_node=False) fields keep their defaults,
    because flax to_bytes/from_bytes carries leaves, not aux data (the
    gate flags ride the proto instead). `source_version` stays at its
    None default: the wire carries it only as an OPTIONAL entry (see
    _delta_to_bytes), and the decode path grafts a slot in when a frame
    actually has one."""
    return cls(**{f.name: jnp.zeros((1,), jnp.float32)
                  for f in dataclasses.fields(cls)
                  if f.metadata.get("pytree_node", True)
                  and f.name != "source_version"})


def _topology_template() -> NodeTopologyDelta:
    """NodeTopologyDelta nests a NodeMetricDelta, so its restore target
    needs the nested structure (leaf shapes are irrelevant)."""
    arrays = {f.name: jnp.zeros((1,), jnp.float32)
              for f in dataclasses.fields(NodeTopologyDelta)
              if f.name not in ("metric", "source_version")
              and f.metadata.get("pytree_node", True)}
    return NodeTopologyDelta(**arrays,
                             metric=_flat_template(NodeMetricDelta))


def _delta_to_bytes(delta) -> bytes:
    """Encode a delta for the wire. An UNVERSIONED delta (source_version
    None, nested metric included) omits the key entirely — byte-for-byte
    the pre-version wire format, pinned by tests/test_sidecar_wire.py's
    frozen frames. A stamped version rides as an optional scalar entry
    (docs/SIDECAR_WIRE.md) so the store's replay guard works across the
    sidecar; foreign decoders ignore keys they don't know."""
    sd = flax.serialization.to_state_dict(delta)
    for node in (sd, sd.get("metric")):
        if isinstance(node, dict) \
                and node.get("source_version", 0) is None:
            node.pop("source_version")
    # in_place=True like flax.to_bytes: the copying path runs the tree
    # through jax tree-utils, which SORTS dict keys and silently
    # reorders the wire map away from the frozen field-order frames
    return flax.serialization.msgpack_serialize(sd, in_place=True)


def _delta_from_bytes(template, body: bytes):
    """Decode a delta frame: frames without a source_version entry (all
    pre-version peers) restore as unversioned; frames carrying one get
    a scalar slot grafted into the template so the stamp survives into
    the store's replay guard."""
    sd = flax.serialization.msgpack_restore(body)
    for node, is_top in ((sd, True), (sd.get("metric"), False)):
        if not isinstance(node, dict):
            continue
        if "source_version" in node:
            slot = jnp.zeros((), jnp.int32)
            if is_top:
                template = template.replace(source_version=slot)
            else:
                template = template.replace(
                    metric=template.metric.replace(source_version=slot))
        else:
            node["source_version"] = None
    return flax.serialization.from_state_dict(template, sd)


_GATE_FIELDS = ("has_taints", "has_spread", "has_anti", "has_aff")
# drift guard: every static PodBatch field MUST ride the proto bits — a
# new pytree_node=False gate silently resetting to its default across
# the wire is the exact bug class the flags transport exists to fix.
# (The tuple stays hand-ordered because bit positions are wire-stable.)
if set(_GATE_FIELDS) != {
        f.name for f in dataclasses.fields(PodBatch)
        if not f.metadata.get("pytree_node", True)}:
    # NOT an assert: it must fire under python -O too — a new static
    # field silently resetting over the wire is the exact bug class the
    # flags transport exists to fix
    raise RuntimeError("PodBatch static fields diverged from the "
                       "sidecar gate-flag transport")


def _pack_gate_flags(pods: PodBatch) -> int:
    return sum(1 << i for i, f in enumerate(_GATE_FIELDS)
               if getattr(pods, f))


def _apply_gate_flags(pods: PodBatch, flags: int) -> PodBatch:
    return pods.replace(**{f: bool(flags & (1 << i))
                           for i, f in enumerate(_GATE_FIELDS)})


class SchedulerSidecarServer:
    """Serves a SchedulerService over the framed-RPC socket."""

    def __init__(self, service: SchedulerService, sock_path: str):
        self.service = service
        self._rpc = RpcServer(sock_path, {
            "PublishSnapshot": (pb.PublishSnapshotRequest, self._publish),
            "IngestDelta": (pb.IngestDeltaRequest, self._ingest),
            "IngestTopology": (pb.IngestTopologyRequest,
                               self._ingest_topology),
            "Schedule": (pb.ScheduleRequest, self._schedule),
            "Summary": (pb.SummaryRequest, self._summary),
        })
        self.sock_path = sock_path

    def close(self) -> None:
        self._rpc.close()

    # --- handlers ---------------------------------------------------------
    def _publish(self, req: pb.PublishSnapshotRequest
                 ) -> pb.PublishSnapshotResponse:
        # no explicit device_put: store.publish places the arrays (with
        # the store's sharding when one is configured)
        snap = flax.serialization.from_bytes(_snapshot_template(),
                                             req.snapshot_msgpack)
        return pb.PublishSnapshotResponse(
            version=self.service.publish(snap))

    def _ingest(self, req: pb.IngestDeltaRequest) -> pb.IngestDeltaResponse:
        delta = _delta_from_bytes(_flat_template(NodeMetricDelta),
                                  req.delta_msgpack)
        # service.ingest, NOT store.ingest: the RPC server is threaded and
        # a delta racing a Schedule call must serialize with the commit
        return pb.IngestDeltaResponse(version=self.service.ingest(delta))

    def _ingest_topology(self, req: pb.IngestTopologyRequest
                         ) -> pb.IngestTopologyResponse:
        """Node add/remove/update churn over the wire as an O(K) row
        patch — WITHOUT this, a sidecar deployment's topology churn
        falls back to the ~10 s full snapshot publish the delta plane
        exists to avoid (store.ingest dispatches on the delta type)."""
        delta = _delta_from_bytes(_topology_template(),
                                  req.delta_msgpack)
        return pb.IngestTopologyResponse(
            version=self.service.ingest(delta))

    def _schedule(self, req: pb.ScheduleRequest) -> pb.ScheduleResponse:
        pods = _apply_gate_flags(
            flax.serialization.from_bytes(_flat_template(PodBatch),
                                          req.pods_msgpack),
            req.gate_flags)
        result = self.service.schedule(
            pods, pod_names=list(req.pod_names) or None)
        # per-call values: the shared last_* attributes can already
        # reflect a RACING ingest/schedule on another connection thread
        version, elapsed = self.service.last_schedule_info()
        return pb.ScheduleResponse(
            assignment=np.asarray(result.assignment,
                                  np.int32).tolist(),
            chosen_score=np.asarray(result.chosen_score,
                                    np.float32).tolist(),
            numa_zone=np.asarray(result.numa_zone, np.int32).tolist(),
            gang_failed=np.asarray(result.gang_failed, bool).tolist(),
            snapshot_version=version,
            elapsed_seconds=elapsed)

    def _summary(self, _req: pb.SummaryRequest) -> pb.SummaryResponse:
        return pb.SummaryResponse(json=json.dumps(self.service.summary()))


class SchedulerSidecarClient:
    """The edge side: typed objects in, numpy out."""

    def __init__(self, sock_path: str, timeout: float = 60.0):
        self._rpc = RpcClient(sock_path, timeout=timeout)

    def publish(self, snapshot: ClusterSnapshot) -> int:
        resp = self._rpc.call(
            "PublishSnapshot",
            pb.PublishSnapshotRequest(
                snapshot_msgpack=flax.serialization.to_bytes(snapshot)),
            pb.PublishSnapshotResponse)
        return resp.version

    def ingest(self, delta: NodeMetricDelta) -> int:
        resp = self._rpc.call(
            "IngestDelta",
            pb.IngestDeltaRequest(
                delta_msgpack=_delta_to_bytes(delta)),
            pb.IngestDeltaResponse)
        return resp.version

    def ingest_topology(self, delta: NodeTopologyDelta) -> int:
        resp = self._rpc.call(
            "IngestTopology",
            pb.IngestTopologyRequest(
                delta_msgpack=_delta_to_bytes(delta)),
            pb.IngestTopologyResponse)
        return resp.version

    def schedule(self, pods: PodBatch,
                 pod_names: Optional[Sequence[str]] = None):
        resp = self._rpc.call(
            "Schedule",
            pb.ScheduleRequest(
                pods_msgpack=flax.serialization.to_bytes(pods),
                pod_names=list(pod_names or []),
                gate_flags=_pack_gate_flags(pods)),
            pb.ScheduleResponse)
        return {
            "assignment": np.asarray(resp.assignment, np.int32),
            "chosen_score": np.asarray(resp.chosen_score, np.float32),
            "numa_zone": np.asarray(resp.numa_zone, np.int32),
            "gang_failed": np.asarray(resp.gang_failed, bool),
            "snapshot_version": resp.snapshot_version,
            "elapsed_seconds": resp.elapsed_seconds,
        }

    def summary(self) -> dict:
        resp = self._rpc.call("Summary", pb.SummaryRequest(),
                              pb.SummaryResponse)
        return json.loads(resp.json)
