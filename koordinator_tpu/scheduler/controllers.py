"""Host-side lifecycle controllers owned by scheduler plugins: Reservation
reconciliation and the gang (PodGroup) state machine.

Capability parity (SURVEY.md 2.1):
- ReservationController (plugins/reservation/controller/): phase
  transitions Pending -> Available (scheduled), TTL expiry -> Expired,
  AllocateOnce fully-consumed -> Succeeded, and terminal-object garbage
  collection.
- GangDirectory (plugins/coscheduling/core/{gang,gang_cache}.go): gangs
  come from PodGroup CRs or lightweight pod annotations; tracks member
  arrival (quorum), assumed counts, and the Permit WaitTime barrier — a
  gang whose quorum never assembles within wait_time has its assumed
  members released (the reference rejects the waiting pods). The
  reference's per-pod ScheduleCycle bookkeeping (gang.go:71-78, which
  batches one attempt per member before retrying) maps onto the batched
  core directly: one schedule_batch invocation IS one gang schedule cycle
  — every member gets exactly one attempt per device program, so the
  cycle-validity machinery reduces to the per-batch all-or-nothing
  rollback already enforced on device.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from koordinator_tpu.api import extension
from koordinator_tpu.api import types as api

GC_DURATION_SECONDS = 24 * 3600.0  # terminal reservations kept for a day


class ReservationController:
    """Reconciles Reservation phase/expiry (controller.go:195-230)."""

    def __init__(self, gc_seconds: float = GC_DURATION_SECONDS):
        self.gc_seconds = gc_seconds
        self._terminal_at: Dict[str, float] = {}

    def reconcile(self, reservations: List[api.Reservation],
                  now: float) -> List[api.Reservation]:
        """Advance phases in place; returns the survivors (GC removes
        long-terminal objects from the list)."""
        # drop tracking for names no longer in the input: externally
        # deleted objects must not leave stale terminal timestamps that
        # would prematurely GC a later same-named reservation (and the
        # map must not grow unboundedly in a long-running controller)
        live = {r.meta.name for r in reservations}
        for stale in set(self._terminal_at) - live:
            del self._terminal_at[stale]
        out: List[api.Reservation] = []
        for r in reservations:
            name = r.meta.name
            if r.phase == "Pending" and r.node_name:
                r.phase = "Available"
            # ttl_seconds <= 0 means never expire (TTLSeconds=0 disables
            # expiration in the reference)
            if r.phase in ("Pending", "Available") and r.create_time > 0 \
                    and r.ttl_seconds > 0 \
                    and now - r.create_time > r.ttl_seconds:
                r.phase = "Expired"
            if r.phase == "Available" and r.allocate_once and r.allocated:
                covered = all(
                    r.allocated.get(k, 0.0) >= v - 0.5
                    for k, v in r.requests.items())
                if covered:
                    r.phase = "Succeeded"
            if r.phase in ("Expired", "Succeeded", "Failed"):
                first = self._terminal_at.setdefault(name, now)
                if now - first > self.gc_seconds:
                    self._terminal_at.pop(name, None)
                    continue  # garbage collected
            else:
                self._terminal_at.pop(name, None)
            out.append(r)
        return out


@dataclasses.dataclass
class GangRecord:
    """One gang's host state (core/gang.go:43-99).

    `assumed` holds every member the scheduler placed (waiting at Permit
    OR already bound); `bound` is the subset past Bind. The match policy
    decides which of those counts toward minMember satisfaction
    (core/core.go:157-174 IsGangMinSatisfied):
    - only-waiting: only members still waiting at the Permit barrier
    - waiting-and-running: every assumed member
    - once-satisfied (default): every assumed member, and satisfaction
      LATCHES — once reached, the gang stays satisfied forever even if
      members terminate (gang.go:59-62 OnceResourceSatisfied)
    """

    name: str
    min_member: int = 1
    total_member: int = 0
    mode: str = "Strict"          # Strict | NonStrict
    match_policy: str = "once-satisfied"
    wait_time_seconds: float = 600.0
    gang_group: tuple = ()        # gangs bundled for bind (gang.go:169-171)
    from_cr: bool = False         # PodGroup CR is authoritative for spec
    members: set = dataclasses.field(default_factory=set)
    assumed: set = dataclasses.field(default_factory=set)
    bound: set = dataclasses.field(default_factory=set)
    once_satisfied: bool = False
    first_assumed_at: Optional[float] = None
    last_assumed_at: float = 0.0   # most recent mark_assumed time (re-arm
    #   floor when satisfaction drops with waiters still at the barrier)
    timeout_count: int = 0

    @property
    def quorum(self) -> bool:
        return len(self.members) >= self.min_member

    @property
    def satisfied(self) -> bool:
        if self.match_policy == "only-waiting":
            return len(self.assumed - self.bound) >= self.min_member
        if self.match_policy == "waiting-and-running":
            return len(self.assumed) >= self.min_member
        return self.once_satisfied or len(self.assumed) >= self.min_member


class GangDirectory:
    """The gangCache equivalent feeding GangState snapshot columns."""

    def __init__(self, default_wait_time_seconds: float = 600.0):
        self.default_wait_time = default_wait_time_seconds
        self.gangs: Dict[str, GangRecord] = {}

    # -- ingest (onPodGroupAdd / onPodAdd) -----------------------------------

    def upsert_pod_group(self, pg: api.PodGroup) -> GangRecord:
        g = self.gangs.get(pg.meta.name)
        if g is None:
            g = self.gangs[pg.meta.name] = GangRecord(name=pg.meta.name)
        g.from_cr = True
        g.min_member = pg.min_member
        g.mode = pg.mode
        g.match_policy = pg.match_policy
        g.wait_time_seconds = pg.wait_time_seconds or self.default_wait_time
        if not g.gang_group:
            g.gang_group = (pg.meta.name,)
        return g

    def add_pod(self, gang_name: str, pod_uid: str,
                min_member: Optional[int] = None,
                annotations: Optional[dict] = None) -> GangRecord:
        """Pods may declare gangs by annotation without a PodGroup CR
        (gang_cache.go onPodAdd creates the gang lazily); a CR-backed
        gang's spec is authoritative — pod annotations never override it.
        `annotations` is the raw pod annotation map; the full gang spec
        (mode/match-policy/wait-time/groups) is parsed from it through
        extension.parse_gang_annotations (TryInitByPodConfig)."""
        g = self.gangs.get(gang_name)
        if g is None:
            g = self.gangs[gang_name] = GangRecord(
                name=gang_name, wait_time_seconds=self.default_wait_time,
                gang_group=(gang_name,))
        if not g.from_cr:
            if annotations is not None:
                spec = extension.parse_gang_annotations(annotations)
                if spec is not None and spec["name"] == gang_name:
                    g.min_member = spec["min_member"]
                    g.mode = spec["mode"]
                    g.match_policy = spec["match_policy"]
                    if spec["wait_time_seconds"]:
                        g.wait_time_seconds = spec["wait_time_seconds"]
                    g.gang_group = tuple(spec["groups"])
            if min_member is not None:
                g.min_member = min_member
        g.members.add(pod_uid)
        g.total_member = len(g.members)
        return g

    def remove_pod(self, gang_name: str, pod_uid: str) -> None:
        g = self.gangs.get(gang_name)
        if g is None:
            return
        g.members.discard(pod_uid)
        g.assumed.discard(pod_uid)
        g.bound.discard(pod_uid)
        self._sync_timer(g)
        g.total_member = len(g.members)
        # annotation-created gangs vanish with their last member; a
        # CR-backed record keeps its spec until the CR is deleted
        if not g.members and not g.from_cr:
            del self.gangs[gang_name]

    def delete_pod_group(self, name: str) -> None:
        self.gangs.pop(name, None)

    # -- scheduling feedback -------------------------------------------------

    def mark_assumed(self, gang_name: str, pod_uid: str,
                     now: float) -> None:
        g = self.gangs.get(gang_name)
        if g is None:
            return
        g.assumed.add(pod_uid)
        g.last_assumed_at = max(g.last_assumed_at, now)
        if g.first_assumed_at is None:
            g.first_assumed_at = now
        if len(g.assumed) >= g.min_member:
            g.once_satisfied = True  # gang.go:62 latch (setResourceSatisfied)
        if g.satisfied:
            g.first_assumed_at = None  # barrier passed; no timeout pending

    def mark_bound(self, gang_name: str, pod_uid: str) -> None:
        """Bind moved the member past the Permit barrier: for the
        only-waiting match policy it stops counting toward minMember."""
        g = self.gangs.get(gang_name)
        if g is None or pod_uid not in g.assumed:
            return
        g.bound.add(pod_uid)
        self._sync_timer(g)

    @staticmethod
    def _sync_timer(g: GangRecord) -> None:
        """Keep the Permit timer consistent with the waiting set: no
        waiters -> no pending timeout; waiters on an UNsatisfied gang ->
        a running timer (re-armed from the latest assume when a bind or
        member loss dropped satisfaction after the timer was cleared, so
        stranded waiters still expire and release their capacity)."""
        if g.assumed == g.bound:
            g.first_assumed_at = None
        elif not g.satisfied and g.first_assumed_at is None:
            g.first_assumed_at = g.last_assumed_at

    def group_satisfied(self, gang_name: str) -> bool:
        """A gang goes to bind only when EVERY gang in its group is
        satisfied (AnnotationGangGroups contract; Permit waits otherwise).
        Unknown group members count as unsatisfied — the group cannot
        complete until they register."""
        g = self.gangs.get(gang_name)
        if g is None:
            return False
        for name in (g.gang_group or (gang_name,)):
            other = self.gangs.get(name)
            if other is None or not other.satisfied:
                return False
        return True

    def expire_waits(self, now: float) -> List[str]:
        """The Permit WaitTime barrier: gangs waiting past wait_time get
        their assumed members released (core.go:311-341 rejection of
        waiting pods), at GANG GROUP granularity — rejectGangGroupById
        releases every sibling gang's waiting members too, so one starved
        gang cannot strand a half-assumed group. Returns the timed-out
        gang names (including siblings released by group rejection); the
        caller unbinds/requeues those pods."""
        timed_out: List[str] = []
        released = set()
        for g in list(self.gangs.values()):
            if g.first_assumed_at is None or g.satisfied:
                continue
            if now - g.first_assumed_at > g.wait_time_seconds:
                for name in (g.gang_group or (g.name,)):
                    sib = self.gangs.get(name)
                    if sib is None or name in released:
                        continue
                    # any timer is dead after a group rejection, whether
                    # or not this sibling had waiters
                    sib.first_assumed_at = None
                    # bound members are past Permit; only waiting ones are
                    # rejected — for EVERY gang in the group, satisfied or
                    # not (rejectGangGroupById iterates all waiting pods
                    # whose gang is in the group, core.go:362-381)
                    if sib.assumed == sib.bound:
                        continue  # nothing waiting to reject
                    sib.assumed = set(sib.bound)
                    sib.timeout_count += 1
                    released.add(name)
                    timed_out.append(name)
        return timed_out

    # -- snapshot feed -------------------------------------------------------

    def to_pod_groups(self) -> List[api.PodGroup]:
        """Typed rows for SnapshotBuilder.add_gang (member counts +
        assumed ride along)."""
        return [api.PodGroup(meta=api.ObjectMeta(name=g.name),
                             min_member=g.min_member,
                             total_member=g.total_member,
                             mode=g.mode,
                             match_policy=g.match_policy,
                             wait_time_seconds=g.wait_time_seconds)
                for g in self.gangs.values()]

    def feed_builder(self, builder) -> None:
        """Feed every gang into a SnapshotBuilder with its assumed count
        and match-policy satisfied latch (what the device gates read)."""
        for pg in self.to_pod_groups():
            g = self.gangs[pg.meta.name]
            builder.add_gang(pg, assumed=len(g.assumed),
                             satisfied=g.satisfied)

    def assumed_count(self, gang_name: str) -> int:
        g = self.gangs.get(gang_name)
        return len(g.assumed) if g else 0

    def summary(self) -> dict:
        """The gang service endpoint payload (frameworkext services)."""
        return {g.name: {"minMember": g.min_member,
                         "members": len(g.members),
                         "assumed": len(g.assumed),
                         "bound": len(g.bound),
                         "matchPolicy": g.match_policy,
                         "satisfied": g.satisfied,
                         "gangGroup": list(g.gang_group or (g.name,)),
                         "timeouts": g.timeout_count}
                for g in self.gangs.values()}
