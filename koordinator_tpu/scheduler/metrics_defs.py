"""Scheduler metric series (pkg/scheduler/metrics/metrics.go parity plus
the TPU per-batch kernel timing series from SURVEY.md §5).

Instantiated against a Registry so tests can assert on a private one; the
default wiring (SchedulerService) uses the process-global registry.

Family names come from the shared name registry
(koordinator_tpu/metrics/registry.py) and are re-exported here; the
koordlint metric-registry pass rejects bare literals so the catalogs
cannot drift.
"""

from __future__ import annotations

from koordinator_tpu.metrics import Registry, global_registry
from koordinator_tpu.metrics.registry import (  # noqa: F401  (re-export)
    SCHEDULER_COMPILE_CACHE_HITS,
    SCHEDULER_COMPILE_CACHE_MISSES,
    SCHEDULER_COST_DRIFT_CHECKS,
    SCHEDULER_CYCLE_PHASE_SECONDS,
    SCHEDULER_DEGRADATION_LEVEL,
    SCHEDULER_DEGRADED_CYCLES,
    SCHEDULER_DELTA_REJECTED,
    SCHEDULER_FAILURES_CLASSIFIED,
    SCHEDULER_GUARD_TRIPS,
    SCHEDULER_HBM_BYTES_IN_USE,
    SCHEDULER_HBM_BYTES_PEAK,
    SCHEDULER_JOURNAL_APPENDS,
    SCHEDULER_JOURNAL_BYTES,
    SCHEDULER_MEMWATCH_LEAK_EVENTS,
    SCHEDULER_MESH_SHRINK_EVENTS,
    SCHEDULER_MESH_SIZE,
    SCHEDULER_PODS_SCHEDULED,
    SCHEDULER_PRECOMPILE_SECONDS,
    SCHEDULER_QUARANTINED_INPUTS,
    SCHEDULER_RECOVERY_COMPILE_SECONDS,
    SCHEDULER_RECOVERY_REPLAYED_RECORDS,
    SCHEDULER_RECOVERY_REPLAY_SECONDS,
    SCHEDULER_RECOVERY_SECONDS,
    SCHEDULER_SCHEDULE_BATCH_KERNEL_SECONDS,
    SCHEDULER_SCHEDULE_CYCLE_SECONDS,
    SCHEDULER_SCHEDULING_TIMEOUT,
    SCHEDULER_SLO_BUDGET_REMAINING,
    SCHEDULER_SLO_BURN_RATE,
    SCHEDULER_SNAPSHOT_VERSION,
    SCHEDULER_TRACE_SPANS_DROPPED,
)

# device-time scale: schedule_batch is ~0.5ms-1s depending on chunk size
KERNEL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0, 2.5)

# host-span scale: a journal append is ~100us, a cold full-gate dispatch
# tens of seconds — the phase histogram must resolve both ends
PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class SchedulerMetrics:
    def __init__(self, registry: Registry = None):
        r = registry if registry is not None else global_registry()
        # kept for the koordtrace export surface: obs/export.py renders
        # this registry's expose() next to the span dump
        self.registry = r
        self.scheduling_timeout = r.counter(
            SCHEDULER_SCHEDULING_TIMEOUT,
            "Scheduling cycles that exceeded the watchdog budget "
            "(scheduler_monitor.go)", labels=("profile",))
        self.cycle_seconds = r.histogram(
            SCHEDULER_SCHEDULE_CYCLE_SECONDS,
            "End-to-end batch scheduling cycle latency (snapshot read to "
            "post-commit publish)")
        self.kernel_seconds = r.histogram(
            SCHEDULER_SCHEDULE_BATCH_KERNEL_SECONDS,
            "Device time of the schedule_batch program per batch "
            "(jax-profiler-annotated region, blocked on the assignment "
            "readback)", buckets=KERNEL_BUCKETS)
        self.pods_scheduled = r.counter(
            SCHEDULER_PODS_SCHEDULED,
            "Pods through the batched commit by result",
            labels=("result",))  # placed | unschedulable
        self.snapshot_version = r.gauge(
            SCHEDULER_SNAPSHOT_VERSION,
            "Version of the device-resident cluster snapshot last "
            "published")
        # resilience layer (docs/DESIGN.md "Failure model & degradation
        # ladder"): every runtime failure, guard trip, quarantined input
        # row, and degraded cycle is countable per class
        self.failures_classified = r.counter(
            SCHEDULER_FAILURES_CLASSIFIED,
            "Device-program failures by FailureClass "
            "(errorhandler.classify_failure)", labels=("failure_class",))
        self.guard_trips = r.counter(
            SCHEDULER_GUARD_TRIPS,
            "Device health-guard trips by defect class "
            "(scheduler/guards.py packed-word bits)", labels=("defect",))
        self.quarantined_inputs = r.counter(
            SCHEDULER_QUARANTINED_INPUTS,
            "Input rows quarantined by the health guards",
            labels=("kind",))  # node | pod
        self.degraded_cycles = r.counter(
            SCHEDULER_DEGRADED_CYCLES,
            "Scheduling cycles run below the normal ladder level "
            "(probe cycles included)", labels=("level",))
        self.degradation_level = r.gauge(
            SCHEDULER_DEGRADATION_LEVEL,
            "Current degradation-ladder level (0 = normal; "
            "frameworkext.DegradationLadder.LEVELS order)")
        self.delta_rejected = r.counter(
            SCHEDULER_DELTA_REJECTED,
            "Snapshot deltas rejected by the store's version guard "
            "(out-of-order / duplicate replay)", labels=("reason",))
        # crash recovery (docs/DESIGN.md "Crash recovery & mesh
        # elasticity"): the commit journal's write volume, what replay
        # had to re-derive after a crash, and the mesh's elasticity
        self.journal_appends = r.counter(
            SCHEDULER_JOURNAL_APPENDS,
            "Chunk commit records durably appended to the commit "
            "journal (scheduler/journal.py)")
        self.journal_bytes = r.counter(
            SCHEDULER_JOURNAL_BYTES,
            "Bytes durably appended to the commit journal")
        self.recovery_replayed = r.counter(
            SCHEDULER_RECOVERY_REPLAYED_RECORDS,
            "Journaled chunk records replayed (asserted bit-identical, "
            "never re-appended) while resuming an interrupted batch — "
            "in-process retry or restart recovery")
        self.recovery_seconds = r.histogram(
            SCHEDULER_RECOVERY_SECONDS,
            "Wall-clock of SchedulerService.recover(): checkpoint "
            "restore + journal replay until the store is re-derived")
        self.mesh_shrink_events = r.counter(
            SCHEDULER_MESH_SHRINK_EVENTS,
            "Ladder transitions INTO the mesh-shrink rung (device lost "
            "with >= 2 survivors; the mesh rebuilds over the survivors)")
        self.mesh_size = r.gauge(
            SCHEDULER_MESH_SIZE,
            "Devices in the mesh the last scheduling cycle considered "
            "usable (survivors on the mesh-shrink rung, 1 on "
            "single_device, the full fleet otherwise)")
        # warm-start layer (docs/DESIGN.md "Compile cache & columnar
        # packing"): program requests the AOT compile cache answered
        # without an XLA compile vs those that had to compile, the
        # warmer's per-program cost, and recovery time split into what
        # replay actually spent vs what compilation cost on top
        self.compile_cache_hits = r.counter(
            SCHEDULER_COMPILE_CACHE_HITS,
            "Cycle-program requests the compile cache served without "
            "an XLA compilation (in-memory memo or persistent-cache "
            "absorbed lowering)")
        self.compile_cache_misses = r.counter(
            SCHEDULER_COMPILE_CACHE_MISSES,
            "Cycle-program requests that cost a real XLA compilation "
            "(new working-set point, contract change, or cold cache)")
        self.precompile_seconds = r.histogram(
            SCHEDULER_PRECOMPILE_SECONDS,
            "Per-program wall time of the AOT warmer "
            "(compilecache.precompile.warm: lower + compile + persist)")
        self.recovery_replay_seconds = r.histogram(
            SCHEDULER_RECOVERY_REPLAY_SECONDS,
            "Recovery wall time minus compilation: checkpoint restore "
            "+ journal replay proper (the floor a warm cache drives "
            "recovery toward)")
        self.recovery_compile_seconds = r.histogram(
            SCHEDULER_RECOVERY_COMPILE_SECONDS,
            "XLA compile-or-retrieve time inside "
            "SchedulerService.recover() (near zero with a warmed "
            "compile cache)")
        # koordtrace observability plane (docs/OBSERVABILITY.md): the
        # span ring's overflow count and the per-phase breakdown of
        # cycle time — phase label values come from obs/phases.py, and
        # every closed host span feeds its duration here via the
        # tracer's observer hook
        self.trace_spans_dropped = r.counter(
            SCHEDULER_TRACE_SPANS_DROPPED,
            "koordtrace span records dropped by ring-buffer overflow "
            "(oldest-first; raise the tracer capacity if nonzero)")
        self.cycle_phase_seconds = r.histogram(
            SCHEDULER_CYCLE_PHASE_SECONDS,
            "Wall time of one koordtrace host span within a scheduling "
            "cycle, by phase (obs/phases.py names: admit, dispatch, "
            "device_wait, journal_append, publish, ...)",
            labels=("phase",), buckets=PHASE_BUCKETS)
        # koordcost resource/SLO plane (docs/OBSERVABILITY.md
        # "SLO objectives & error budgets"): per-objective burn-rate
        # windows and remaining budget (obs/slo.py), device-memory
        # telemetry sampled at the dispatch/device_wait span boundaries
        # with its leak sentinel (obs/memwatch.py), and the static
        # cost-drift gate's verdict ledger (tools/costcheck.py)
        self.slo_budget_remaining = r.gauge(
            SCHEDULER_SLO_BUDGET_REMAINING,
            "Fraction of the error budget left per SLO objective over "
            "its longest window (1 = untouched, 0 = exhausted)",
            labels=("objective",))
        self.slo_burn_rate = r.gauge(
            SCHEDULER_SLO_BURN_RATE,
            "Error-budget burn rate per objective and window (1 = "
            "burning exactly the budget; >1 exhausts it early)",
            labels=("objective", "window"))
        self.hbm_bytes_in_use = r.gauge(
            SCHEDULER_HBM_BYTES_IN_USE,
            "Device memory in use at the last memwatch sample "
            "(device.memory_stats() on TPU; live-buffer walk on "
            "backends without allocator stats)", labels=("device",))
        self.hbm_bytes_peak = r.gauge(
            SCHEDULER_HBM_BYTES_PEAK,
            "Peak device memory seen by memwatch since service start "
            "(allocator peak when the backend reports one, else the "
            "high-water mark of the sampled in-use series)",
            labels=("device",))
        self.memwatch_leak_events = r.counter(
            SCHEDULER_MEMWATCH_LEAK_EVENTS,
            "Leak-sentinel firings: device memory in use grew "
            "monotonically across the full sentinel window of "
            "committed cycles", labels=("device",))
        self.cost_drift_checks = r.counter(
            SCHEDULER_COST_DRIFT_CHECKS,
            "Static cost-baseline comparisons by verdict "
            "(tools/costcheck.py gate runs)", labels=("result",))
