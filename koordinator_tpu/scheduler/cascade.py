"""Stage 1 of the Filter->Score gate cascade.

The reference scheduler never scores what it hasn't filtered: its Filter
stage prunes the node set before Score ever runs (koordinator's
Filter/Score cycle; cf. Tesserae's two-level prune-then-place, arxiv
2508.04953). The batched kernel historically had no Filter stage at all —
every gate, cheap or heavy, ran over the full [P, N] pair space. This
module is that missing Filter stage, split in two layers:

- `static_gates`: the cheap per-batch node gates (schedulable +
  nodeSelector + LoadAware usage + taint forbids) shared by BOTH cascade
  modes. Moved here out of `core.schedule_batch` so the cascade and the
  legacy full-width path run one implementation and cannot drift.
- `stage1_mask`: the cascade-only candidate mask — the static gates AND
  batch-start resource fit AND batch-start quota-ceiling admission
  (ops/feasibility kernels).

Soundness contract (why `cascade=True` is placement-preserving): within
one `schedule_batch` call, node `requested` and quota `used` are MONOTONE
— scatter-commits only add non-negative accepted requests — so a
(pod, node) pair that fails the batch-start fit or quota ceiling fails
the corresponding exact gate in every commit round. Folding the stage-1
mask into the static gates therefore removes only pairs the rounds would
have rejected anyway: masked scores, top-k order, and every downstream
prefix gate see identical inputs, and placements are bit-for-bit the
same with the cascade on or off. `cascade=False` is the conformance
oracle (tests/test_cascade.py pins equality on the full-gate workload).

What stage 2 buys: with the cheap mask folded in early, `core` narrows
the HEAVY per-pair machinery — the [P, N, I] device prefilter/score, the
[P, N, Z] zone prefilter/score, and the policy combined-fit — to the
class-prefix rows that can possibly engage them (the numa_prefix /
gpu_prefix packing contracts), padding pass-through rows back in. On the
constraint-sparse flagship workload those tensors shrink ~10x.

The [P, N] mask follows the snapshot's node-column sharding on a mesh
(parallel/mesh.candidate_mask_sharding): pods replicate, node columns
shard, so stage 1 is embarrassingly parallel over chips — the compiled
stage-1 HLO over sharded inputs contains zero collectives
(tools/mesh_flagship_smoke.py pins that structurally), and
parallel.shardops.stage1_mask_sharded is the explicit shard_map form
for callers composing the mask outside one jitted program. Pad rows
appended by parallel.pad_nodes_to_mesh are killed here: schedulable is
False and allocatable zero, so their columns are all-False in every
stage-1 mask.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from koordinator_tpu import obs
from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.ops import feasibility
from koordinator_tpu.scheduler.batching import MAX_NODE_SCORE
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.schema import (
    ClusterSnapshot,
    MAX_QUOTA_DEPTH,
    NodeState,
    PodBatch,
    shape_contract,
)


@shape_contract(
    nodes="NodeState", pods="PodBatch", cfg="LoadAwareConfig",
    _returns=("bool[P~pad:invalid,N~pad:false]",
              "?f32[P~pad:any,N~pad:any]"),
    _pad="unschedulable (padded) node columns are False everywhere; "
         "taint_penalty is None when the batch models no tolerations "
         "(has_taints False — the gate compiles out)")
def static_gates(nodes: NodeState, pods: PodBatch,
                 cfg: loadaware.LoadAwareConfig
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """(static_ok bool[P, N], taint_penalty f32[P, N] or None): the
    cheap round-invariant node gates of the batch.

    - nodeSelector: sel_match[sel_id, label_group[n]]; -1 matches all.
    - LoadAware filter: round-invariant (it reads only NodeMetric-derived
      columns and thresholds, never assume state — load_aware.go:123-254
      touches no NodeInfo.requested), so it is computed once per batch.
    - TaintToleration (vanilla-framework plugin the reference's extender
      wraps): forbid on untolerated NoSchedule/NoExecute, penalize
      untolerated PreferNoSchedule. Matrices ride (toleration-set x
      taint-group) exactly like the selector gate; `has_taints` False
      means the batch carries no toleration modeling (synthetic fast
      path) and the gates compile out (taint_penalty None).
    """
    with obs.phase(obs_phases.PHASE_STAGE1_STATIC):
        sel = jnp.maximum(pods.selector_id, 0)
        sel_ok = (pods.selector_id[:, None] < 0) | \
            pods.selector_match[sel][:, nodes.label_group]       # [P, N]
        la_ok = loadaware.filter_mask(nodes, pods, cfg)
        static_ok = la_ok & sel_ok & nodes.schedulable[None, :]  # [P, N]
        if pods.has_taints:
            tol_row = pods.tol_forbid[jnp.maximum(pods.toleration_id, 0)]
            static_ok &= ~tol_row[:, nodes.taint_group]          # [P, N]
            prefer_cnt = pods.tol_prefer[
                jnp.maximum(pods.toleration_id, 0)][:, nodes.taint_group]
            taint_penalty = prefer_cnt / jnp.maximum(
                jnp.max(pods.tol_prefer), 1.0) * MAX_NODE_SCORE
        else:
            taint_penalty = None
        return static_ok, taint_penalty


@shape_contract(
    snap="ClusterSnapshot", pods="PodBatch",
    static_ok="bool[P~pad:invalid,N~pad:false]",
    _returns="bool[P~pad:invalid,N~pad:false]",
    _pad="a SUPERSET of every commit round's node-column feasibility; "
         "never applied to reservation slot columns (consumers draw "
         "from the slot's own hold)")
def stage1_mask(snap: ClusterSnapshot, pods: PodBatch,
                static_ok: jnp.ndarray,
                fit_dims: Optional[tuple] = None,
                quota_depth: int = MAX_QUOTA_DEPTH) -> jnp.ndarray:
    """bool[P, N]: the stage-1 candidate mask — `static_ok` pruned by
    batch-start resource fit and quota-ceiling admission.

    MASK CONTRACT: the mask is a SUPERSET of every commit round's exact
    feasibility on node columns (monotone batch-start state; see module
    docstring), so ANDing it into the static gates is placement-
    preserving. It must NOT be applied to reservation slot columns: a
    consumer draws from the slot's own hold, not the node's open pool,
    so a full node legitimately admits its slot's consumers
    (core keeps `static_base` for the slot columns).
    """
    with obs.phase(obs_phases.PHASE_STAGE1_MASK):
        mask = static_ok & feasibility.resource_fit(
            snap.nodes.allocatable, snap.nodes.requested, pods.requests,
            fit_dims)
        mask &= feasibility.quota_ceiling_ok(
            snap.quotas, pods, quota_depth, fit_dims)[:, None]
        return mask


@shape_contract(mask="bool[P~pad:invalid,N~pad:false]",
                _returns="i32[P~pad:any]",
                _pad="pad pod rows count their surviving pad-invariant "
                     "columns — observability only, masked by valid")
def candidate_counts(mask: jnp.ndarray) -> jnp.ndarray:
    """i32[P]: surviving candidate nodes per pod — the cascade's
    observability hook (a zero row is a pod stage 1 already proved
    unschedulable; tools/cascade_smoke.py asserts on it)."""
    return jnp.sum(mask.astype(jnp.int32), axis=1)
