"""API layer: the protocol (QoS classes, priority bands, resource kinds,
label/annotation keys) and CRD-equivalent typed objects.

Mirrors the capability surface of the reference's `apis/` tree
(apis/extension, apis/slo, apis/scheduling, apis/quota, apis/configuration).
"""

from koordinator_tpu.api.extension import (  # noqa: F401
    QoSClass,
    PriorityClass,
    ResourceKind,
    PRIORITY_BANDS,
    priority_class_of,
    translate_resource_by_priority,
)
