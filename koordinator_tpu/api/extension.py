"""The annotation/label protocol: QoS classes, priority bands, extended
resources, and the fixed resource-dimension enum used by the device tensors.

Capability parity with the reference's `apis/extension/` package:
- QoS classes LSE/LSR/LS/BE/SYSTEM (apis/extension/qos.go:23-28)
- Priority bands koord-prod 9000-9999 / mid 7000-7999 / batch 5000-5999 /
  free 3000-3999 (apis/extension/priority.go:38-48)
- Batch/Mid extended resources kubernetes.io/batch-cpu|batch-memory|
  mid-cpu|mid-memory (apis/extension/resource.go:26-29)
- Device resources gpu-core/gpu-memory/gpu-memory-ratio/rdma/fpga
  (apis/extension/device_share.go:38-55)

TPU-native addition: `ResourceKind` is the *fixed, static* resource axis of
every device tensor. XLA requires static shapes, so instead of the reference's
open-ended `map[ResourceName]Quantity`, cluster state is columnar over this
enum. Canonical device units: CPU-like dims in millicores, memory-like dims in
MiB (float32-safe up to ~16 PiB), device dims in device-specific units.
"""

from __future__ import annotations

import enum
from typing import Mapping, Optional


class QoSClass(enum.IntEnum):
    """Koordinator QoS classes (apis/extension/qos.go:23-28).

    Integer-valued so pod QoS can live in an int8 device column.
    """

    NONE = 0
    SYSTEM = 1
    LSE = 2  # latency-sensitive exclusive: pinned cpus, no sharing
    LSR = 3  # latency-sensitive reserved: pinned cpus, sharable with BE
    LS = 4   # latency-sensitive (shared pool)
    BE = 5   # best effort (reclaimed/batch resources)

    @classmethod
    def parse(cls, s: str) -> "QoSClass":
        try:
            return cls[s.upper()] if s else cls.NONE
        except KeyError:
            return cls.NONE


class PriorityClass(enum.IntEnum):
    """Koordinator priority classes (apis/extension/priority.go:29-35)."""

    NONE = 0
    FREE = 1
    BATCH = 2
    MID = 3
    PROD = 4

    @classmethod
    def parse(cls, s: str) -> "PriorityClass":
        key = s.replace("koord-", "").upper() if s else ""
        try:
            return cls[key] if key else cls.NONE
        except KeyError:
            return cls.NONE

    @property
    def text(self) -> str:
        return "" if self is PriorityClass.NONE else f"koord-{self.name.lower()}"


# Priority value bands (apis/extension/priority.go:38-48): class -> (min, max).
PRIORITY_BANDS: Mapping[PriorityClass, tuple] = {
    PriorityClass.PROD: (9000, 9999),
    PriorityClass.MID: (7000, 7999),
    PriorityClass.BATCH: (5000, 5999),
    PriorityClass.FREE: (3000, 3999),
}

DEFAULT_PRIORITY_CLASS = PriorityClass.NONE


def priority_class_of(priority: Optional[int], label: str = "",
                      priority_class_name: str = "") -> PriorityClass:
    """Resolve a pod's PriorityClass from its priority value, override
    label, or k8s PriorityClassName.

    Mirrors GetPodPriorityClassRaw/getPriorityClassByPriority
    (apis/extension/priority.go:73-103): the `koordinator.sh/priority-class`
    label wins; a koord-* PriorityClassName is next (it covers priority
    values outside the koordinator bands — a cluster's unrelated
    PriorityClass that merely happens to be named "batch" must NOT be
    treated as koordinator Batch, so only the koord- prefixed names
    resolve here); otherwise the numeric priority is matched against the
    bands.
    """
    if label:
        parsed = PriorityClass.parse(label)
        if parsed is not PriorityClass.NONE:
            return parsed
    if priority_class_name and priority_class_name.startswith("koord-"):
        parsed = PriorityClass.parse(priority_class_name)
        if parsed is not PriorityClass.NONE:
            return parsed
    if priority is None:
        return PriorityClass.NONE
    for cls, (lo, hi) in PRIORITY_BANDS.items():
        if lo <= priority <= hi:
            return cls
    return DEFAULT_PRIORITY_CLASS


def selector_matches(selector: Mapping[str, str],
                     labels: Mapping[str, str]) -> bool:
    """Exact-match label selector; empty selector matches everything
    (util.GetFastLabelSelector semantics for matchLabels-only selectors).
    Single shared implementation — webhook matching, quota profiles, and
    slo-config node strategies all use this."""
    return all(labels.get(k) == v for k, v in selector.items())


class ResourceKind(enum.IntEnum):
    """The static resource axis R of all device tensors.

    Covers the reference's standard + extended resources:
    cpu/memory (k8s core), batch-* / mid-* overcommit resources
    (apis/extension/resource.go:26-29), and device resources
    (apis/extension/device_share.go).
    """

    CPU = 0            # millicores
    MEMORY = 1         # MiB
    BATCH_CPU = 2      # millicores (BE-tier overcommit)
    BATCH_MEMORY = 3   # MiB
    MID_CPU = 4        # millicores (Mid-tier overcommit)
    MID_MEMORY = 5     # MiB
    GPU_CORE = 6       # percent-of-one-GPU units (100 == one full GPU)
    GPU_MEMORY = 7     # MiB
    EPHEMERAL_STORAGE = 8  # MiB
    RDMA = 9           # percent units
    FPGA = 10          # percent units

    @classmethod
    def dim(cls) -> int:
        return len(cls)


NUM_RESOURCES = ResourceKind.dim()

# k8s-style resource-name strings <-> ResourceKind.
RESOURCE_NAMES: Mapping[str, ResourceKind] = {
    "cpu": ResourceKind.CPU,
    "memory": ResourceKind.MEMORY,
    "kubernetes.io/batch-cpu": ResourceKind.BATCH_CPU,
    "kubernetes.io/batch-memory": ResourceKind.BATCH_MEMORY,
    "kubernetes.io/mid-cpu": ResourceKind.MID_CPU,
    "kubernetes.io/mid-memory": ResourceKind.MID_MEMORY,
    "koordinator.sh/gpu-core": ResourceKind.GPU_CORE,
    "koordinator.sh/gpu-memory": ResourceKind.GPU_MEMORY,
    "ephemeral-storage": ResourceKind.EPHEMERAL_STORAGE,
    "koordinator.sh/rdma": ResourceKind.RDMA,
    "koordinator.sh/fpga": ResourceKind.FPGA,
}

# Label / annotation keys (apis/extension/constants.go).
DOMAIN_PREFIX = "koordinator.sh/"
SCHEDULING_DOMAIN_PREFIX = "scheduling.koordinator.sh"
NODE_DOMAIN_PREFIX = "node.koordinator.sh"
POD_DOMAIN_PREFIX = "pod.koordinator.sh"

LABEL_POD_QOS = DOMAIN_PREFIX + "qosClass"
LABEL_POD_PRIORITY = DOMAIN_PREFIX + "priority"
LABEL_POD_PRIORITY_CLASS = DOMAIN_PREFIX + "priority-class"
LABEL_PODGROUP = "pod-group.scheduling.sigs.k8s.io"  # gang membership
LABEL_QUOTA_NAME = "quota.scheduling.koordinator.sh/name"
ANNOTATION_RESOURCE_SPEC = SCHEDULING_DOMAIN_PREFIX + "/resource-spec"
ANNOTATION_RESOURCE_STATUS = SCHEDULING_DOMAIN_PREFIX + "/resource-status"
ANNOTATION_RESERVATION_ALLOCATED = SCHEDULING_DOMAIN_PREFIX + "/reservation-allocated"
ANNOTATION_EXTENDED_RESOURCE_SPEC = NODE_DOMAIN_PREFIX + "/extended-resource-spec"
ANNOTATION_NODE_CPU_NORMALIZATION_RATIO = NODE_DOMAIN_PREFIX + "/cpu-normalization-ratio"
# per-node colocation strategy override (node_colocation.go:23) — a JSON
# partial ColocationStrategy merged over the cluster/selector strategy
ANNOTATION_NODE_COLOCATION_STRATEGY = NODE_DOMAIN_PREFIX + "/colocation-strategy"
# float ratios that take precedence over the strategy's reclaim percents
LABEL_CPU_RECLAIM_RATIO = NODE_DOMAIN_PREFIX + "/cpu-reclaim-ratio"
LABEL_MEMORY_RECLAIM_RATIO = NODE_DOMAIN_PREFIX + "/memory-reclaim-ratio"
ANNOTATION_NODE_RAW_ALLOCATABLE = NODE_DOMAIN_PREFIX + "/raw-allocatable"
ANNOTATION_NODE_AMPLIFICATION_RATIOS = (
    NODE_DOMAIN_PREFIX + "/resource-amplification-ratio")


def node_cpu_amplification_ratio(annotations: Mapping[str, str]) -> float:
    """The node's published CPU amplification ratio, clamped to >= 1
    (nodenumaresource util.go:65-85). THE one parser for the annotation
    — snapshot builder and host preemption must agree. Lenient on
    malformed values: the validating webhook already rejected those, so
    a bad value reaching here means an out-of-band writer; degrade to
    raw accounting rather than fail ingest."""
    import json
    raw = (annotations or {}).get(ANNOTATION_NODE_AMPLIFICATION_RATIOS, "")
    if not raw:
        return 1.0
    try:
        return max(float(json.loads(raw).get("cpu", 1.0)), 1.0)
    except (ValueError, TypeError, AttributeError):
        return 1.0
ANNOTATION_NODE_RESERVATION = NODE_DOMAIN_PREFIX + "/reservation"
LABEL_NUMA_TOPOLOGY_POLICY = NODE_DOMAIN_PREFIX + "/numa-topology-policy"

# NUMA topology-manager policy codes (apis/extension/numa_aware.go:138-145;
# merged by scheduler/topologymanager.py)
NUMA_POLICY_NONE = 0
NUMA_POLICY_BEST_EFFORT = 1
NUMA_POLICY_RESTRICTED = 2
NUMA_POLICY_SINGLE_NUMA_NODE = 3

_NUMA_POLICY_NAMES = {
    "": NUMA_POLICY_NONE,
    "none": NUMA_POLICY_NONE,
    "besteffort": NUMA_POLICY_BEST_EFFORT,
    "best-effort": NUMA_POLICY_BEST_EFFORT,
    "restricted": NUMA_POLICY_RESTRICTED,
    "singlenumanode": NUMA_POLICY_SINGLE_NUMA_NODE,
    "single-numa-node": NUMA_POLICY_SINGLE_NUMA_NODE,
}


def numa_policy_code(name: str) -> int:
    """Policy string (numa-topology-policy label / kubelet policy, either
    casing) -> code; unknown strings mean none
    (GetNodeNUMATopologyPolicy, numa_aware.go:327)."""
    return _NUMA_POLICY_NAMES.get(name.strip().lower(), NUMA_POLICY_NONE)


_KIND_NAMES = {v: k for k, v in RESOURCE_NAMES.items()}
# tier-translated kinds the webhook erases from the native columns — the
# ones a runtime (NRI/proxy) can only learn through the annotation
EXTENDED_KINDS = (ResourceKind.BATCH_CPU, ResourceKind.BATCH_MEMORY,
                  ResourceKind.MID_CPU, ResourceKind.MID_MEMORY)


def encode_extended_resource_spec(requests: Mapping,
                                  limits: Mapping) -> str:
    """Pod requests/limits -> the `extended-resource-spec` annotation value
    (apis/extension ExtendedResourceSpec; written by the webhook's
    extended-resource mutator, read by the NRI/proxy container contexts —
    protocol/container_context.go:93-120). Only the extended tiers ride
    the annotation; empty string when none apply. Container-granular in
    the reference, pod-granular here like the rest of the agent."""
    import json as _json

    def pick(rl):
        return {_KIND_NAMES[k]: float(v) for k, v in rl.items()
                if k in EXTENDED_KINDS}

    req, lim = pick(requests), pick(limits)
    if not req and not lim:
        return ""
    return _json.dumps({"requests": req, "limits": lim})


def parse_extended_resource_spec(annotations: Mapping) -> tuple:
    """annotation -> (requests, limits) ResourceLists (the NRI/proxy-side
    GetExtendedResourceSpec); ({}, {}) when absent or malformed."""
    import json as _json

    raw = annotations.get(ANNOTATION_EXTENDED_RESOURCE_SPEC, "")
    if not raw:
        return {}, {}
    try:
        spec = _json.loads(raw)
    except ValueError:
        return {}, {}

    def pick(d):
        out = {}
        for name, v in (d or {}).items():
            kind = RESOURCE_NAMES.get(name)
            if kind is not None:
                out[kind] = float(v)
        return out

    return pick(spec.get("requests")), pick(spec.get("limits"))


# combined GPU request conveniences (device_share.go:36-46; deviceshare
# utils.go:110-125 translates them to core + memory-ratio pairs)
RESOURCE_GPU_COMBINED = "koordinator.sh/gpu"
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"


def normalize_gpu_request(requests_by_name: Mapping,
                          parse=float) -> tuple:
    """({name: qty} minus combined GPU names, percent). The percent maps
    to BOTH gpu-core and gpu-memory-ratio (deviceshare utils.go:110-125):
    `koordinator.sh/gpu: X` means X percent of a GPU; `nvidia.com/gpu: N`
    means N whole GPUs (100N percent). `parse` converts raw quantity
    values (pass the caller's k8s-quantity parser; bare float would raise
    on suffixed serializations)."""
    out = dict(requests_by_name)
    percent = 0.0
    if RESOURCE_GPU_COMBINED in out:
        percent += parse(out.pop(RESOURCE_GPU_COMBINED))
    if RESOURCE_NVIDIA_GPU in out:
        percent += parse(out.pop(RESOURCE_NVIDIA_GPU)) * 100.0
    return out, percent


# --- SystemQOS (apis/extension/system_qos.go) -------------------------------
ANNOTATION_NODE_SYSTEM_QOS_RESOURCE = (
    NODE_DOMAIN_PREFIX + "/system-qos-resource")


def parse_system_qos_resource(annotations: Mapping) -> Optional[dict]:
    """node annotation -> {"cpuset": "0-3", "cpus": [0,1,2,3],
    "exclusive": bool} or None when absent/malformed/empty.
    CPUSetExclusive defaults to TRUE (system_qos.go:36-39): exclusive
    system cores are carved out of every other tier's usable set."""
    import json as _json

    raw = annotations.get(ANNOTATION_NODE_SYSTEM_QOS_RESOURCE, "")
    if not raw:
        return None
    try:
        data = _json.loads(raw)
        spec = str(data.get("cpuset", ""))
        if not spec:
            return None
        cpus: list = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "-" in part:
                lo, hi = part.split("-", 1)
                cpus.extend(range(int(lo), int(hi) + 1))
            else:
                cpus.append(int(part))
        exclusive = data.get("cpusetExclusive")
        return {"cpuset": spec, "cpus": sorted(set(cpus)),
                "exclusive": True if exclusive is None else bool(exclusive)}
    except (ValueError, TypeError, AttributeError):
        return None


# --- gang annotation protocol (apis/extension/coscheduling.go:26-61) -------
ANNOTATION_GANG_PREFIX = "gang.scheduling.koordinator.sh"
ANNOTATION_GANG_NAME = ANNOTATION_GANG_PREFIX + "/name"
ANNOTATION_GANG_MIN_NUM = ANNOTATION_GANG_PREFIX + "/min-available"
ANNOTATION_GANG_TOTAL_NUM = ANNOTATION_GANG_PREFIX + "/total-number"
ANNOTATION_GANG_MODE = ANNOTATION_GANG_PREFIX + "/mode"
ANNOTATION_GANG_WAIT_TIME = ANNOTATION_GANG_PREFIX + "/waiting-time"
ANNOTATION_GANG_GROUPS = ANNOTATION_GANG_PREFIX + "/groups"
# written BY the scheduler when a gang group's Permit wait expires
ANNOTATION_GANG_TIMEOUT = ANNOTATION_GANG_PREFIX + "/timeout"
ANNOTATION_GANG_MATCH_POLICY = ANNOTATION_GANG_PREFIX + "/match-policy"

GANG_MODE_STRICT = "Strict"
GANG_MODE_NON_STRICT = "NonStrict"
GANG_MATCH_ONLY_WAITING = "only-waiting"
GANG_MATCH_WAITING_AND_RUNNING = "waiting-and-running"
GANG_MATCH_ONCE_SATISFIED = "once-satisfied"
_GANG_MATCH_POLICIES = (GANG_MATCH_ONLY_WAITING,
                        GANG_MATCH_WAITING_AND_RUNNING,
                        GANG_MATCH_ONCE_SATISFIED)


def parse_gang_annotations(annotations: Mapping) -> Optional[dict]:
    """Pod annotations -> gang spec dict, or None when the pod declares no
    gang. Lenient exactly like TryInitByPodConfig (core/gang.go:120-175):
    illegal mode/match-policy/wait-time fall back to defaults with the
    value dropped, min-available <= 0 or unparseable clamps to 1
    (deviation: the reference leaves such a gang uninitialized and
    rejects its pods in PreFilter; clamping keeps them schedulable as a
    trivially-satisfied gang), total-number below min is raised to min.
    The gang's own name is always part of its group. The `pod-group`
    label (sigs convention) also names a gang when the annotation is
    absent."""
    name = annotations.get(ANNOTATION_GANG_NAME, "") or \
        annotations.get(LABEL_PODGROUP, "")
    if not name:
        return None
    try:
        min_num = int(annotations.get(ANNOTATION_GANG_MIN_NUM, "1"))
    except ValueError:
        min_num = 1
    if min_num <= 0:
        min_num = 1
    try:
        total = int(annotations.get(ANNOTATION_GANG_TOTAL_NUM, str(min_num)))
    except ValueError:
        total = min_num
    total = max(total, min_num)
    mode = annotations.get(ANNOTATION_GANG_MODE, GANG_MODE_STRICT)
    if mode not in (GANG_MODE_STRICT, GANG_MODE_NON_STRICT):
        mode = GANG_MODE_STRICT
    policy = annotations.get(ANNOTATION_GANG_MATCH_POLICY,
                             GANG_MATCH_ONCE_SATISFIED)
    if policy not in _GANG_MATCH_POLICIES:
        policy = GANG_MATCH_ONCE_SATISFIED
    try:
        wait = float(annotations.get(ANNOTATION_GANG_WAIT_TIME, "0"))
    except ValueError:
        wait = 0.0
    groups: list = []
    raw_groups = annotations.get(ANNOTATION_GANG_GROUPS, "")
    if raw_groups:
        import json as _json
        try:
            parsed = _json.loads(raw_groups)
            if isinstance(parsed, list):
                groups = [str(x) for x in parsed]
        except ValueError:
            groups = []
    if groups and name not in groups:
        # a gang is always a member of its own group — otherwise group
        # rejection/expiry could never release its waiting members
        groups.insert(0, name)
    return {"name": name, "min_member": min_num, "total_member": total,
            "mode": mode, "match_policy": policy,
            "wait_time_seconds": wait if wait > 0 else None,
            "groups": groups or [name]}


def translate_resource_by_priority(kind: ResourceKind,
                                   priority_class: PriorityClass) -> ResourceKind:
    """Map cpu/memory to the priority tier's extended resource.

    Mirrors TranslateResourceNameByPriorityClass
    (apis/extension/resource.go:52-57): Batch pods request batch-cpu/
    batch-memory; Mid pods request mid-cpu/mid-memory; Prod/None keep the
    native resource.
    """
    if priority_class is PriorityClass.BATCH:
        if kind is ResourceKind.CPU:
            return ResourceKind.BATCH_CPU
        if kind is ResourceKind.MEMORY:
            return ResourceKind.BATCH_MEMORY
    elif priority_class is PriorityClass.MID:
        if kind is ResourceKind.CPU:
            return ResourceKind.MID_CPU
        if kind is ResourceKind.MEMORY:
            return ResourceKind.MID_MEMORY
    return kind
