"""CRD-equivalent typed objects.

These are the host-side typed objects that the snapshot builder columnarizes
into device tensors, and that the control-plane components (slo_controller,
descheduler, webhook) produce/consume. They mirror the reference CRDs:

- NodeMetric / NodeSLO            (apis/slo/v1alpha1, SURVEY.md 2.6)
- Reservation / Device / PodMigrationJob (apis/scheduling/v1alpha1)
- PodGroup / ElasticQuota         (vendored scheduling.sigs.k8s.io types)
- ClusterColocationProfile        (apis/config/v1alpha1)
- NodeResourceTopology            (topology.node.k8s.io)

ResourceList is a plain dict keyed by ResourceKind in canonical device units
(cpu-like: millicores, memory-like: MiB) — see api/extension.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Tuple

from koordinator_tpu.api.extension import (
    PriorityClass,
    QoSClass,
    ResourceKind,
    priority_class_of,
)

ResourceList = Dict[ResourceKind, float]


def add_resources(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def namespaced_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class Taint:
    """Node taint (core/v1 Taint; consumed by the descheduler's
    RemovePodsViolatingNodeTaints compat plugin)."""

    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"   # NoSchedule | NoExecute | PreferNoSchedule


@dataclasses.dataclass
class NodeSelectorRequirement:
    """One nodeAffinity match expression (core/v1
    NodeSelectorRequirement): In | NotIn | Exists | DoesNotExist |
    Gt | Lt over a node label key."""

    key: str = ""
    operator: str = "In"
    values: List[str] = dataclasses.field(default_factory=list)

    def matches(self, labels: Dict[str, str]) -> bool:
        present = self.key in labels
        value = labels.get(self.key, "")
        if self.operator == "In":
            return present and value in self.values
        if self.operator == "NotIn":
            return not present or value not in self.values
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        try:
            if self.operator == "Gt":
                return present and int(value) > int(self.values[0])
            if self.operator == "Lt":
                return present and int(value) < int(self.values[0])
        except (ValueError, IndexError):
            return False
        return False


@dataclasses.dataclass
class TopologySpreadConstraint:
    """core/v1 TopologySpreadConstraint subset: spread pods matching
    `label_selector` (own-namespace) across the node-label domains of
    `topology_key`, keeping the count difference within `max_skew`.
    DoNotSchedule filters; ScheduleAnyway only prefers (and is treated as
    a no-op gate here — the LoadAware ranking already spreads load)."""

    max_skew: int = 1
    topology_key: str = "topology.kubernetes.io/zone"
    when_unsatisfiable: str = "DoNotSchedule"  # | ScheduleAnyway
    label_selector: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PodAffinityTerm:
    """core/v1 PodAffinityTerm subset (requiredDuringScheduling...):
    co-locate with (`anti`=False) or keep away from (`anti`=True) pods
    matching `label_selector` (own namespace) within the node-label
    domains of `topology_key`."""

    topology_key: str = "kubernetes.io/hostname"
    label_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    anti: bool = False


@dataclasses.dataclass
class Toleration:
    """Pod toleration: empty key tolerates EVERY taint key (the blanket
    operator-Exists toleration critical DaemonSets carry); empty value
    tolerates any value of the key; empty effect tolerates every
    effect (core/v1 Toleration.ToleratesTaint semantics)."""

    key: str = ""
    value: str = ""
    effect: str = ""

    def tolerates(self, taint: "Taint") -> bool:
        if self.key and self.key != taint.key:
            return False
        if self.value and self.value != taint.value:
            return False
        return not self.effect or self.effect == taint.effect


@dataclasses.dataclass
class Pod:
    """A pending or running pod, pre-resolved to the koordinator protocol.

    `requests`/`limits` aggregate the pod's containers (the reference uses
    PodRequestsAndLimits, estimator/default_estimator.go:62).
    """

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    requests: ResourceList = dataclasses.field(default_factory=dict)
    limits: ResourceList = dataclasses.field(default_factory=dict)
    priority: Optional[int] = None
    node_name: str = ""          # "" == pending
    scheduler_name: str = "koord-scheduler"
    priority_class_name: str = ""  # k8s PriorityClass reference
    priority_class_label: str = ""
    qos_label: str = ""
    gang_name: str = ""          # pod-group label (coscheduling)
    quota_name: str = ""         # elastic quota label
    is_daemonset: bool = False
    # NUMA / fine-grained CPU request (annotation resource-spec)
    cpu_bind_policy: str = ""    # "", FullPCPUs, SpreadByPCPUs
    required_cpu_bind: bool = False
    # zone granted to a RUNNING bound pod (annotation resource-status,
    # numa_aware.go) — restored into NodeState.numa_free at snapshot build
    allocated_numa_zone: int = -1
    # device requests/allocations (apis/extension/device_share.go):
    # gpu-core/gpu-memory/rdma/fpga ride in `requests`; an explicit
    # gpu-memory-ratio request is carried separately (it is converted
    # against the node's per-GPU memory at filter time)
    gpu_memory_ratio: float = 0.0
    # instance indices granted to a RUNNING pod (the device-allocation
    # annotation) — restored into DeviceState free at snapshot build
    allocated_gpu_minors: Tuple[int, ...] = ()
    allocated_rdma_inst: int = -1
    allocated_fpga_inst: int = -1
    # reservation this RUNNING pod consumes (reservation-allocated
    # annotation) — its zone/instance charges stay inside the hold
    reservation_name: str = ""
    # node selection
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    # required nodeAffinity match expressions, ANDed with node_selector
    # (requiredDuringSchedulingIgnoredDuringExecution; preferred terms are
    # a score concern the LoadAware ranking subsumes)
    node_affinity: List[NodeSelectorRequirement] = dataclasses.field(
        default_factory=list)
    # topology spread: EVERY constraint is modeled on device (hard ones
    # gate by skew, ScheduleAnyway ones only score) — multi-constraint
    # pods (zone + hostname, the upstream default profile) are gated by
    # each via the carrier matrix
    spread_constraints: List[TopologySpreadConstraint] = dataclasses.field(
        default_factory=list)
    # inter-pod affinity: EVERY required term is modeled on device,
    # affinity and anti-affinity alike (carrier matrices)
    pod_affinity: List[PodAffinityTerm] = dataclasses.field(
        default_factory=list)
    # controller owner (ReplicaSet/StatefulSet...) — the migration
    # arbitrator bounds blast radius per workload (arbitrator/filter.go)
    owner_workload: str = ""     # "namespace/name" of the controller
    workload_replicas: int = 0
    # device request (gpu-core percent, gpu-memory MiB) folded into requests
    phase: str = "Pending"
    # lifecycle/status consumed by the descheduler compat plugins
    start_time: float = 0.0      # unix seconds; 0 = unknown
    restart_count: int = 0       # sum over containers
    tolerations: List[Toleration] = dataclasses.field(default_factory=list)
    # simplified topologySpreadConstraint (one per pod): spread over the
    # node-label key with bounded skew; "" = none
    spread_topology_key: str = ""
    spread_max_skew: int = 1

    @property
    def qos(self) -> QoSClass:
        return QoSClass.parse(self.qos_label)

    @property
    def priority_class(self) -> PriorityClass:
        return priority_class_of(self.priority, self.priority_class_label,
                                 self.priority_class_name)


@dataclasses.dataclass
class NUMAZone:
    """One NUMA node's capacity on a machine (NodeResourceTopology zone)."""

    cpus_milli: float = 0.0
    memory_mib: float = 0.0
    # bitmask of logical CPU ids in this zone (python int bitmask, host-side)
    cpuset: int = 0


@dataclasses.dataclass
class NodeResourceTopology:
    """Per-node CPU/NUMA topology (topology.node.k8s.io NodeResourceTopology;
    reported by koordlet statesinformer, SURVEY.md 2.2)."""

    node_name: str = ""
    zones: List[NUMAZone] = dataclasses.field(default_factory=list)
    cpus_per_core: int = 2  # SMT siblings per physical core
    kubelet_reserved_cpuset: int = 0
    policy: str = "None"    # kubelet topology manager policy
    # CPU share pools (states_noderesourcetopology.go:359-360): the cpus
    # LS pods may roam = all cpus - LSE/LSR-pinned - exclusive SystemQOS;
    # the BE pool additionally serves suppress-managed BE pods
    ls_share_pool: str = ""  # cpuset list string, "" = not reported
    be_share_pool: str = ""


@dataclasses.dataclass
class Node:
    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    allocatable: ResourceList = dataclasses.field(default_factory=dict)
    unschedulable: bool = False
    topology: Optional[NodeResourceTopology] = None
    taints: List[Taint] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ResourceMap:
    """Point-in-time resource usage (slo/v1alpha1 ResourceMap)."""

    resources: ResourceList = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AggregatedUsage:
    """Percentile usage over a duration window
    (nodemetric_types.go aggregated metrics: p50/p90/p95/p99)."""

    # aggregation type ("avg"/"p50"/"p90"/"p95"/"p99") -> usage
    usages: Dict[str, ResourceList] = dataclasses.field(default_factory=dict)
    duration_seconds: float = 0.0


@dataclasses.dataclass
class PodMetricInfo:
    namespace: str = ""
    name: str = ""
    priority_class: PriorityClass = PriorityClass.NONE
    usage: ResourceList = dataclasses.field(default_factory=dict)

    @property
    def namespaced_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass
class HostApplicationMetricInfo:
    """Usage of one out-of-band host application
    (nodemetric_types.go:67-78)."""

    name: str = ""
    usage: ResourceList = dataclasses.field(default_factory=dict)
    priority_class: PriorityClass = PriorityClass.NONE
    qos: QoSClass = QoSClass.NONE


@dataclasses.dataclass
class NodeMetric:
    """Per-node usage report written by the node agent
    (slo/v1alpha1 NodeMetric, nodemetric_types.go:39-123)."""

    node_name: str = ""
    update_time: float = 0.0           # unix seconds
    report_interval_seconds: float = 60.0
    node_usage: ResourceList = dataclasses.field(default_factory=dict)
    system_usage: ResourceList = dataclasses.field(default_factory=dict)
    aggregated: List[AggregatedUsage] = dataclasses.field(default_factory=list)
    pods_metric: List[PodMetricInfo] = dataclasses.field(default_factory=list)
    host_app_metric: List[HostApplicationMetricInfo] = dataclasses.field(
        default_factory=list)
    prod_reclaimable: ResourceList = dataclasses.field(default_factory=dict)

    def is_expired(self, expiration_seconds: float,
                   now: Optional[float] = None) -> bool:
        """isNodeMetricExpired (plugins/loadaware/helper.go)."""
        now = time.time() if now is None else now
        return (self.update_time <= 0
                or now - self.update_time >= expiration_seconds)

    def aggregated_usage(self, agg_type: str,
                         duration_seconds: float = 0.0) -> Optional[ResourceList]:
        """getTargetAggregatedUsage (plugins/loadaware/helper.go): pick the
        window with the largest duration <= requested (or the max window when
        duration==0), then the requested percentile."""
        if not self.aggregated:
            return None
        best = None
        for agg in self.aggregated:
            if duration_seconds <= 0 or agg.duration_seconds <= duration_seconds:
                if best is None or agg.duration_seconds > best.duration_seconds:
                    best = agg
        if best is None:
            best = min(self.aggregated, key=lambda a: a.duration_seconds)
        return best.usages.get(agg_type)


# --- NodeSLO ----------------------------------------------------------------


@dataclasses.dataclass
class ResourceThresholdStrategy:
    """resourceUsedThresholdWithBE (slo/v1alpha1 nodeslo_types.go): drives
    koordlet cpusuppress."""

    enable: bool = False
    cpu_suppress_threshold_percent: float = 65.0
    cpu_suppress_policy: str = "cpuset"  # cpuset | cfsQuota
    memory_evict_threshold_percent: float = 70.0
    memory_evict_lower_percent: float = 0.0  # default threshold-2
    cpu_evict_be_usage_threshold_percent: float = 90.0
    cpu_evict_satisfaction_lower_percent: float = 0.0  # 0 = evict disabled
    cpu_evict_satisfaction_upper_percent: float = 40.0
    cpu_evict_time_window_seconds: float = 60.0


@dataclasses.dataclass
class CPUBurstStrategy:
    policy: str = "none"  # none | cpuBurstOnly | cfsQuotaBurstOnly | auto
    cpu_burst_percent: float = 1000.0
    cfs_quota_burst_percent: float = 300.0
    cfs_quota_burst_period_seconds: float = -1.0
    share_pool_threshold_percent: float = 50.0


@dataclasses.dataclass
class ResourceQOSStrategy:
    """Per-QoS-tier cgroup knobs (resourceQOS in nodeslo_types.go), flattened
    to the fields the TPU build's qosmanager enforces."""

    # qos tier -> {knob: value}; knobs e.g. groupIdentity, memoryQOS priority,
    # resctrl llc/mba percent
    tiers: Dict[str, Dict[str, float]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SystemStrategy:
    min_free_kbytes_factor: float = 100.0
    watermark_scale_factor: float = 150.0
    memcg_reap_enabled: bool = False


@dataclasses.dataclass
class HostApplication:
    """Out-of-band application running directly on the host, under agent
    QoS management (slo/v1alpha1 host_application.go:24-34). When
    `cgroup_dir` is empty the agent derives it from the QoS class
    (host-latency-sensitive/<name> or host-best-effort/<name>,
    util/host_application.go:28-46)."""

    name: str = ""
    priority_class: PriorityClass = PriorityClass.NONE
    qos: QoSClass = QoSClass.NONE
    cgroup_dir: str = ""   # explicit relative cgroup dir override


@dataclasses.dataclass
class NodeSLO:
    node_name: str = ""
    threshold: ResourceThresholdStrategy = dataclasses.field(
        default_factory=ResourceThresholdStrategy)
    cpu_burst: CPUBurstStrategy = dataclasses.field(
        default_factory=CPUBurstStrategy)
    resource_qos: ResourceQOSStrategy = dataclasses.field(
        default_factory=ResourceQOSStrategy)
    system: SystemStrategy = dataclasses.field(default_factory=SystemStrategy)
    host_applications: List[HostApplication] = dataclasses.field(
        default_factory=list)
    # per-block IO throttles (BlkIOQOS blocks, nodeslo_types.go:188-196)
    blkio_blocks: List["BlockCfg"] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class BlockCfg:
    """One block device's IO config (nodeslo_types.go BlockCfg + IOCfg).
    `name` is a device path for type "device", or "namespace/claim" for
    type "podvolume" (resolved to the bound volume through the PVC
    informer's map)."""

    name: str = ""
    block_type: str = "device"     # device | podvolume | volumegroup
    read_iops: int = 0             # 0 = unlimited (feature off)
    write_iops: int = 0
    read_bps: int = 0
    write_bps: int = 0
    io_weight_percent: int = 100


@dataclasses.dataclass
class PersistentVolumeClaim:
    """The slice of corev1 PVC the agent needs: claim identity -> bound
    volume name (statesinformer/impl/states_pvc.go volumeNameMap)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    volume_name: str = ""


# --- Scheduling CRDs --------------------------------------------------------


@dataclasses.dataclass
class ReservationCondition:
    """Status condition on a Reservation (reservation_types.go
    ReservationCondition; written by the scheduler's error handler on
    unschedulable reserve pods)."""

    type: str = "Scheduled"     # Scheduled | Ready
    status: str = "False"       # True | False
    reason: str = ""
    message: str = ""
    last_probe_time: float = 0.0
    last_transition_time: float = 0.0


REASON_RESERVATION_UNSCHEDULABLE = "Unschedulable"
REASON_RESERVATION_SCHEDULED = "Scheduled"


@dataclasses.dataclass
class Reservation:
    """Reserved capacity scheduled like a pod, later consumed by matching
    owners (scheduling/v1alpha1 reservation_types.go:27-64)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    requests: ResourceList = dataclasses.field(default_factory=dict)
    owner_label_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    ttl_seconds: float = 86400.0
    allocate_once: bool = True
    node_name: str = ""         # set when the reservation is scheduled
    phase: str = "Pending"      # Pending|Available|Succeeded|Failed|Expired
    allocated: ResourceList = dataclasses.field(default_factory=dict)
    # uids of pods whose allocation is included in `allocated`
    # (status.currentOwners, reservation_types.go) — lets the assume
    # cache retire a consumer the moment the CR accounts for it, so the
    # consumer is never subtracted from the hold twice
    current_owners: Tuple[str, ...] = ()
    create_time: float = 0.0
    conditions: List[ReservationCondition] = dataclasses.field(
        default_factory=list)
    # fine-grained holds granted when the reserve pod was scheduled (the
    # device-allocation / resource-status annotations on the reservation;
    # restored to consumers, transformer.go:240-291)
    allocated_gpu_minors: Tuple[int, ...] = ()
    allocated_numa_zone: int = -1
    required_cpu_bind: bool = False
    gpu_memory_ratio: float = 0.0

    def matches(self, pod: Pod) -> bool:
        sel = self.owner_label_selector
        return bool(sel) and all(
            pod.meta.labels.get(k) == v for k, v in sel.items())


@dataclasses.dataclass
class PodGroup:
    """Gang definition (scheduling.sigs.k8s.io PodGroup)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    min_member: int = 1
    total_member: int = 0
    mode: str = "Strict"           # Strict | NonStrict
    # How minMember satisfaction is counted (gang.go:68 GangMatchPolicy):
    # once-satisfied (default; latches forever), waiting-and-running
    # (waiting-at-Permit + bound), only-waiting (waiting-at-Permit only)
    match_policy: str = "once-satisfied"
    wait_time_seconds: float = 600.0
    phase: str = "Pending"


@dataclasses.dataclass
class ElasticQuota:
    """Hierarchical quota node (scheduling.sigs.k8s.io ElasticQuota with
    koordinator's hierarchy annotations; SURVEY.md 2.1 ElasticQuota plugin)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    parent: str = ""               # parent quota name ("" == root child)
    min: ResourceList = dataclasses.field(default_factory=dict)
    max: ResourceList = dataclasses.field(default_factory=dict)
    shared_weight: ResourceList = dataclasses.field(default_factory=dict)
    is_parent: bool = False
    allow_lent_resource: bool = True
    tree_id: str = ""              # multi-quota-tree support
    namespaces: List[str] = dataclasses.field(default_factory=list)
    allow_force_update: bool = False


@dataclasses.dataclass
class ElasticQuotaProfile:
    """Quota-tree provisioning profile (quota.koordinator.sh/v1alpha1;
    pkg/quota-controller/profile): generates a root ElasticQuota whose min
    tracks the total allocatable of the selected nodes."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    quota_name: str = ""
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    resource_ratio: float = 1.0
    resource_keys: Tuple[ResourceKind, ...] = (ResourceKind.CPU,
                                               ResourceKind.MEMORY)
    tree_id: str = ""


@dataclasses.dataclass
class DeviceInfo:
    """One device on a node (scheduling/v1alpha1 device_types.go)."""

    minor: int = 0
    type: str = "gpu"              # gpu | rdma | fpga
    health: bool = True
    resources: ResourceList = dataclasses.field(default_factory=dict)
    numa_node: int = 0
    pcie_id: str = ""


@dataclasses.dataclass
class Device:
    node_name: str = ""
    devices: List[DeviceInfo] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PodMigrationJob:
    """Descheduler-driven migration (scheduling/v1alpha1
    pod_migration_job_types.go)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    pod_namespace: str = ""
    pod_name: str = ""
    mode: str = "ReservationFirst"  # ReservationFirst | EvictDirectly
    ttl_seconds: float = 300.0
    phase: str = "Pending"  # Pending|Running|Succeeded|Failed
    reservation_name: str = ""
    reason: str = ""


@dataclasses.dataclass
class ClusterColocationProfile:
    """Webhook mutation profile (config/v1alpha1
    cluster_colocation_profile_types.go; webhook mutator
    pod/mutating/cluster_colocation_profile.go:53-157)."""

    meta: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    namespace_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    label_keys_mapping: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotation_keys_mapping: Dict[str, str] = dataclasses.field(default_factory=dict)
    qos_class: str = ""
    priority_class_name: str = ""
    koordinator_priority: Optional[int] = None
    scheduler_name: str = ""
    probability: float = 1.0       # random-percent gating (reference supports %)
    skip_update_resources: bool = False
