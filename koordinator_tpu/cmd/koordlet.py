"""koordlet process: the per-node agent daemon.

Capability parity with `cmd/koordlet/main.go`: flags + feature gates
mapped onto DaemonConfig, graceful shutdown. No leader election — one
agent per node. The host root flag lets the agent run against any mounted
kernel tree (the production default "/", a FakeHost dir in demos)."""

from __future__ import annotations

import argparse
from typing import Optional, Sequence

from koordinator_tpu.cmd.runtime import (
    StopHandle,
    add_metrics_flags,
    attach_metrics_server,
    close_metrics_server,
    parse_feature_gates,
)
from koordinator_tpu.features import new_default_gate
from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
from koordinator_tpu.koordlet.system import Host


def build(argv: Optional[Sequence[str]] = None,
          host: Optional[Host] = None) -> Daemon:
    p = argparse.ArgumentParser(prog="koordlet")
    p.add_argument("--feature-gates", default="")
    p.add_argument("--host-root", default="/")
    p.add_argument("--collect-interval-seconds", type=float, default=1.0)
    p.add_argument("--report-interval-seconds", type=float, default=60.0)
    p.add_argument("--checkpoint-path", default="")
    p.add_argument("--audit-http-port", type=int, default=0)
    add_metrics_flags(p)
    # kubelet /pods pull (kubelet_stub.go flags: --kubelet-* options);
    # empty address keeps the push edge (set_pods) in charge
    p.add_argument("--kubelet-addr", default="")
    p.add_argument("--kubelet-port", type=int, default=10250)
    p.add_argument("--kubelet-scheme", default="https")
    p.add_argument("--kubelet-token-file", default="")
    p.add_argument("--kubelet-insecure-tls", action="store_true")
    p.add_argument("--kubelet-resync-seconds", type=float, default=60.0)
    args = p.parse_args(argv)
    gate = new_default_gate()
    parse_feature_gates(gate, args.feature_gates)
    cfg = DaemonConfig(
        collect_interval_seconds=args.collect_interval_seconds,
        report_interval_seconds=args.report_interval_seconds,
        checkpoint_path=args.checkpoint_path,
        enable_perf_group=gate.enabled("Libpfm4"),
        enable_page_cache=gate.enabled("ColdPageCollector"),
        enable_core_sched=gate.enabled("CoreSched"),
        audit_http_port=(args.audit_http_port
                         if gate.enabled("AuditEventsHTTPHandler") else -1))
    daemon = Daemon(host or Host(args.host_root), cfg)
    if args.kubelet_addr:
        from koordinator_tpu.koordlet.kubelet_stub import (
            KubeletStub,
            PodsPuller,
        )

        token = ""
        if args.kubelet_token_file:
            with open(args.kubelet_token_file, encoding="utf-8") as f:
                token = f.read().strip()
        daemon.pods_puller = PodsPuller(
            KubeletStub(args.kubelet_addr, args.kubelet_port,
                        args.kubelet_scheme, token=token,
                        insecure_tls=args.kubelet_insecure_tls),
            daemon.informer,
            resync_interval_seconds=args.kubelet_resync_seconds)
    # LAST: anything above may raise, and a half-built daemon must not
    # leak a bound /metrics listener
    return attach_metrics_server(daemon, args)


def main(argv: Optional[Sequence[str]] = None,
         host: Optional[Host] = None) -> int:
    daemon = build(argv, host)
    stop = StopHandle().install_signal_handlers()
    try:
        daemon.run(stop.stopped)
    finally:
        close_metrics_server(daemon)
    return 0
