"""koord-manager process: leader-elected control loop hosting the
slo-controller reconcilers, the quota-profile reconciler, and the
admission webhooks.

Capability parity with `cmd/koord-manager/main.go`: feature-gate flags,
leader election (single active manager), health/metrics endpoint, and
graceful shutdown. Controller wiring mirrors
`pkg/slo-controller/*` + `pkg/quota-controller/profile` setup done by the
controller-runtime manager there; cluster state arrives through a
`ClusterSource` (the edge informer plane in production, a fake in tests)
instead of client-go informers.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.cmd.runtime import (
    FileLeaseLock,
    LeaderElector,
    StopHandle,
    add_metrics_flags,
    attach_metrics_server,
    close_metrics_server,
    default_identity,
    parse_feature_gates,
)
from koordinator_tpu.features import FeatureGate, new_default_gate
from koordinator_tpu.quota_controller import QuotaProfileReconciler
from koordinator_tpu.slo_controller.nodemetric import NodeMetricController
from koordinator_tpu.slo_controller.noderesource import (
    NodeResourceController,
    build_inputs,
)
from koordinator_tpu.slo_controller.nodeslo import (
    SLOControllerConfig,
    render_node_slo,
)
from koordinator_tpu.webhook import PodMutator, QuotaTopology


class ClusterSource(Protocol):
    """The manager's view of the cluster (informer plane boundary)."""

    def nodes(self) -> Sequence[api.Node]: ...
    def node_metrics(self) -> Dict[str, api.NodeMetric]: ...
    def pods_by_node(self) -> Dict[str, List[api.Pod]]: ...
    def quota_profiles(self) -> Sequence[api.ElasticQuotaProfile]: ...


class ClusterSink(Protocol):
    """Where reconcile results land (status writeback boundary)."""

    def set_node_batch_resources(self, node: api.Node,
                                 batch_cpu: float, batch_mem: float,
                                 mid_cpu: float, mid_mem: float) -> None: ...
    def set_node_slo(self, slo: api.NodeSLO) -> None: ...


class InMemorySink:
    """Default sink: mutates the node objects, records NodeSLOs."""

    def __init__(self) -> None:
        self.node_slos: Dict[str, api.NodeSLO] = {}

    def set_node_batch_resources(self, node: api.Node, batch_cpu: float,
                                 batch_mem: float, mid_cpu: float,
                                 mid_mem: float) -> None:
        node.allocatable[RK.BATCH_CPU] = batch_cpu
        node.allocatable[RK.BATCH_MEMORY] = batch_mem
        node.allocatable[RK.MID_CPU] = mid_cpu
        node.allocatable[RK.MID_MEMORY] = mid_mem

    def set_node_slo(self, slo: api.NodeSLO) -> None:
        self.node_slos[slo.node_name] = slo


@dataclasses.dataclass
class ManagerConfig:
    reconcile_interval_seconds: float = 30.0
    lease_file: str = "koord-manager.lease"
    enable_leader_election: bool = True
    lease_duration_seconds: float = 15.0
    retry_period_seconds: float = 2.0
    feature_gates: str = ""
    identity: str = ""


class ManagerProcess:
    """The leader-elected reconcile loop."""

    def __init__(self, cfg: ManagerConfig, source: ClusterSource,
                 sink: Optional[ClusterSink] = None,
                 gate: Optional[FeatureGate] = None,
                 slo_config: Optional[SLOControllerConfig] = None,
                 clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self.metrics_server = None
        self.source = source
        self.sink = sink or InMemorySink()
        self.gate = gate or new_default_gate()
        parse_feature_gates(self.gate, cfg.feature_gates)
        self.slo_config = slo_config or SLOControllerConfig()
        self.clock = clock
        self.node_metric_ctl = NodeMetricController()
        self.node_resource_ctl = NodeResourceController()
        self.quota_reconciler = QuotaProfileReconciler(QuotaTopology())
        # the webhook framework: the edge calls admission.admit(kind, obj)
        # for every write (pkg/webhook/server.go handler registry); set
        # `mutator` (below) when colocation profiles arrive
        from koordinator_tpu.webhook.framework import AdmissionDispatcher
        self.admission = AdmissionDispatcher(
            mutator=None, quota_topology=self.quota_reconciler.topology,
            gate=self.gate)
        self.ticks = 0
        identity = cfg.identity or default_identity()
        self.elector = LeaderElector(
            FileLeaseLock(cfg.lease_file, cfg.lease_duration_seconds),
            identity, cfg.retry_period_seconds, clock=clock)

    @property
    def mutator(self) -> Optional[PodMutator]:
        """ONE mutator slot shared with the admission dispatcher —
        assigning here makes pod admission apply it."""
        return self.admission.mutator

    @mutator.setter
    def mutator(self, value: Optional[PodMutator]) -> None:
        self.admission.mutator = value

    # one reconcile pass over everything the manager owns
    def tick(self, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        nodes = list(self.source.nodes())
        metrics = self.source.node_metrics()
        pods = self.source.pods_by_node()
        if nodes:
            out = self.node_resource_ctl.reconcile(
                build_inputs(nodes, metrics, pods, now=now))
            for i, node in enumerate(nodes):
                if not out["sync_mask"][i]:
                    continue
                self.sink.set_node_batch_resources(
                    node,
                    float(out["batch"][i, 0]), float(out["batch"][i, 1]),
                    float(out["mid"][i, 0]), float(out["mid"][i, 1]))
        for node in nodes:
            self.sink.set_node_slo(render_node_slo(
                self.slo_config, node.meta.name, node.meta.labels))
        for profile in self.source.quota_profiles():
            self.quota_reconciler.reconcile(profile, nodes)
        self.ticks += 1

    def _lead(self, should_stop: Callable[[], bool]) -> None:
        while not should_stop():
            self.tick()
            deadline = time.monotonic() + self.cfg.reconcile_interval_seconds
            while not should_stop() and time.monotonic() < deadline:
                time.sleep(min(0.05, self.cfg.retry_period_seconds))

    def run(self, stop: Callable[[], bool]) -> None:
        if self.cfg.enable_leader_election:
            self.elector.run(self._lead, stop)
        else:
            self._lead(stop)


def build(argv: Optional[Sequence[str]] = None,
          source: Optional[ClusterSource] = None,
          sink: Optional[ClusterSink] = None) -> ManagerProcess:
    p = argparse.ArgumentParser(prog="koord-manager")
    p.add_argument("--feature-gates", default="")
    p.add_argument("--lease-file", default="koord-manager.lease")
    p.add_argument("--enable-leader-election", dest="leader_election",
                   action="store_true", default=True)
    p.add_argument("--disable-leader-election", dest="leader_election",
                   action="store_false")
    p.add_argument("--reconcile-interval-seconds", type=float, default=30.0)
    p.add_argument("--identity", default="")
    add_metrics_flags(p)
    args = p.parse_args(argv)
    cfg = ManagerConfig(
        reconcile_interval_seconds=args.reconcile_interval_seconds,
        lease_file=args.lease_file,
        enable_leader_election=args.leader_election,
        feature_gates=args.feature_gates,
        identity=args.identity)
    if source is None:
        raise SystemExit("koord-manager needs a cluster source (the edge "
                         "informer plane); pass one via build(source=...)")
    return attach_metrics_server(ManagerProcess(cfg, source, sink), args)


def main(argv: Optional[Sequence[str]] = None,
         source: Optional[ClusterSource] = None,
         sink: Optional[ClusterSink] = None) -> int:
    proc = build(argv, source, sink)
    stop = StopHandle().install_signal_handlers()
    try:
        proc.run(stop.stopped)
    finally:
        close_metrics_server(proc)
    return 0
