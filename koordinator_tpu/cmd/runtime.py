"""Shared process runtime: stop signals, lease-based leader election,
feature-gate flags.

Capability parity with the reference's binary entry points
(`cmd/koord-manager/main.go`, `cmd/koord-descheduler`, `cmd/koord-scheduler`):
flag parsing with `--feature-gates=A=true,B=false`, graceful shutdown on
SIGTERM/SIGINT, and single-active leader election. The reference elects
through an apiserver resource lock (resourcelock leases,
cmd/koord-manager/main.go "leader-elect-resource-lock"); the TPU build has
no apiserver, so the lock is a LEASE FILE on the shared state directory —
fcntl-serialized read-modify-write gives the same single-holder guarantee
for processes sharing a filesystem, with the same lease/renew/steal
semantics as client-go's leaderelection package.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import signal
import threading
import time
from typing import Callable, Optional

from koordinator_tpu.features import FeatureGate


class StopHandle:
    """Cooperative shutdown: a predicate components poll, settable from
    signal handlers (the stop channel of the Go mains)."""

    def __init__(self) -> None:
        self._event = threading.Event()

    def stop(self, *_signal_args) -> None:
        self._event.set()

    def stopped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def install_signal_handlers(self) -> "StopHandle":
        """Main-thread only; tests drive stop() directly."""
        signal.signal(signal.SIGTERM, self.stop)
        signal.signal(signal.SIGINT, self.stop)
        return self


@dataclasses.dataclass
class LeaseRecord:
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = 15.0

    def expired(self, now: float) -> bool:
        return now >= self.renew_time + self.lease_duration


class FileLeaseLock:
    """A lease on a file: acquire when free/expired/already-held-by-self,
    renew by bumping renew_time, release by clearing the holder. All
    transitions run under an fcntl lock on a sidecar so two processes
    never interleave read-modify-write (LeaseLock semantics from
    client-go resourcelock, as used by cmd/koord-manager/main.go)."""

    def __init__(self, path: str, lease_duration: float = 15.0):
        self.path = path
        self.lease_duration = lease_duration
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def _locked(self, fn: Callable[[LeaseRecord], Optional[LeaseRecord]]
                ) -> Optional[LeaseRecord]:
        with open(self.path + ".lock", "w") as guard:
            fcntl.flock(guard, fcntl.LOCK_EX)
            try:
                rec = self._read()
                out = fn(rec)
                if out is not None:
                    tmp = self.path + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(json.dumps(dataclasses.asdict(out)))
                    os.replace(tmp, self.path)  # atomic publish
                return out
            finally:
                fcntl.flock(guard, fcntl.LOCK_UN)

    def _read(self) -> LeaseRecord:
        try:
            with open(self.path) as f:
                return LeaseRecord(**json.loads(f.read()))
        except (OSError, ValueError, TypeError):
            return LeaseRecord()

    def holder(self, now: Optional[float] = None) -> str:
        now = time.time() if now is None else now
        rec = self._read()
        return "" if rec.expired(now) else rec.holder

    def try_acquire(self, identity: str, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now

        def txn(rec: LeaseRecord) -> Optional[LeaseRecord]:
            if rec.holder and rec.holder != identity and not rec.expired(now):
                return None
            return LeaseRecord(holder=identity, renew_time=now,
                               lease_duration=self.lease_duration)

        return self._locked(txn) is not None

    def renew(self, identity: str, now: Optional[float] = None) -> bool:
        """Fails when the lease was stolen (we stopped being the holder)."""
        now = time.time() if now is None else now

        def txn(rec: LeaseRecord) -> Optional[LeaseRecord]:
            if rec.holder != identity:
                return None
            return LeaseRecord(holder=identity, renew_time=now,
                               lease_duration=self.lease_duration)

        return self._locked(txn) is not None

    def release(self, identity: str) -> None:
        def txn(rec: LeaseRecord) -> Optional[LeaseRecord]:
            if rec.holder != identity:
                return None
            return LeaseRecord()

        self._locked(txn)


class LeaderElector:
    """client-go leaderelection loop: acquire -> lead while renewing ->
    release on stop / step down on lost lease. `on_started_leading`
    receives a should-stop predicate it must poll; it returning means the
    leadership session ended."""

    def __init__(self, lock: FileLeaseLock, identity: str,
                 retry_period: float = 2.0,
                 clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep):
        self.lock = lock
        self.identity = identity
        self.retry_period = retry_period
        self.clock = clock
        self.sleep = sleep
        self.is_leader = False

    def run(self, on_started_leading: Callable[[Callable[[], bool]], None],
            stop: Callable[[], bool]) -> None:
        while not stop():
            if not self.lock.try_acquire(self.identity, self.clock()):
                self.sleep(self.retry_period)
                continue
            self.is_leader = True
            lost = threading.Event()
            done = threading.Event()

            def renew_loop() -> None:
                # ANY failure to renew — stolen lease or an I/O error on
                # the lease file — must depose this leader: a silently
                # dead renewer while the lease expires is split brain
                try:
                    while not done.is_set() and not lost.is_set():
                        if not self.lock.renew(self.identity, self.clock()):
                            lost.set()  # stolen — step down
                            break
                        done.wait(self.retry_period)
                except Exception:
                    lost.set()

            renewer = threading.Thread(target=renew_loop, daemon=True)
            renewer.start()
            try:
                on_started_leading(lambda: stop() or lost.is_set())
            finally:
                done.set()
                renewer.join()
                self.is_leader = False
                if not lost.is_set():
                    self.lock.release(self.identity)


def parse_feature_gates(gate: FeatureGate, spec: str) -> None:
    """--feature-gates=A=true,B=false (component-base flag syntax)."""
    if spec:
        gate.parse(spec)


def default_identity() -> str:
    return f"{os.uname().nodename}_{os.getpid()}"


def add_metrics_flags(parser) -> None:
    """The shared Prometheus scrape-surface flags every daemon carries."""
    # -1 disables the endpoint (metrics stay in-process)
    parser.add_argument("--metrics-port", type=int, default=-1)
    parser.add_argument("--metrics-host", default="0.0.0.0")


def attach_metrics_server(proc, args):
    """Start the /metrics endpoint on `proc.metrics_server` when the
    flags enable it (every Process/Daemon declares the attribute)."""
    if args.metrics_port >= 0:
        from koordinator_tpu.metrics import global_registry
        from koordinator_tpu.utils.httpserver import MetricsServer

        proc.metrics_server = MetricsServer(global_registry(),
                                            host=args.metrics_host,
                                            port=args.metrics_port)
    return proc


def close_metrics_server(proc) -> None:
    if proc.metrics_server is not None:
        proc.metrics_server.close()
