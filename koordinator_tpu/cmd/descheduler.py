"""koord-descheduler process: leader-elected descheduling cycle.

Capability parity with `cmd/koord-descheduler/main.go` +
`pkg/descheduler/descheduler.go` Run: flags, leader election, the
interval-driven profile loop (CycleRunner), graceful shutdown. Plugin
wiring (LowNodeLoad + migration arbitration) matches the default profile.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Callable, Optional, Sequence

from koordinator_tpu.api import types as api
from koordinator_tpu.cmd.runtime import (
    FileLeaseLock,
    LeaderElector,
    StopHandle,
    add_metrics_flags,
    attach_metrics_server,
    close_metrics_server,
    default_identity,
    parse_feature_gates,
)
from koordinator_tpu.descheduler.framework import CycleRunner, EvictionLimiter
from koordinator_tpu.features import FeatureGate, new_default_gate


@dataclasses.dataclass
class DeschedulerConfig:
    descheduling_interval_seconds: float = 120.0
    lease_file: str = "koord-descheduler.lease"
    enable_leader_election: bool = True
    lease_duration_seconds: float = 15.0
    retry_period_seconds: float = 2.0
    feature_gates: str = ""
    identity: str = ""


class DeschedulerProcess:
    """Hosts a CycleRunner under leader election; `get_nodes` is the
    informer-plane boundary (a fake in tests)."""

    def __init__(self, cfg: DeschedulerConfig,
                 runner: CycleRunner,
                 get_nodes: Callable[[], Sequence[api.Node]],
                 gate: Optional[FeatureGate] = None,
                 clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self.metrics_server = None
        self.runner = runner
        self.get_nodes = get_nodes
        self.gate = gate or new_default_gate()
        parse_feature_gates(self.gate, cfg.feature_gates)
        self.cycles = 0
        identity = cfg.identity or default_identity()
        self.elector = LeaderElector(
            FileLeaseLock(cfg.lease_file, cfg.lease_duration_seconds),
            identity, cfg.retry_period_seconds, clock=clock)

    def _lead(self, should_stop: Callable[[], bool]) -> None:
        while not should_stop():
            self.runner.run_once(self.get_nodes())
            self.cycles += 1
            deadline = (time.monotonic()
                        + self.cfg.descheduling_interval_seconds)
            while not should_stop() and time.monotonic() < deadline:
                time.sleep(min(0.05, self.cfg.retry_period_seconds))

    def run(self, stop: Callable[[], bool]) -> None:
        if self.cfg.enable_leader_election:
            self.elector.run(self._lead, stop)
        else:
            self._lead(stop)


def build(argv: Optional[Sequence[str]] = None,
          runner: Optional[CycleRunner] = None,
          get_nodes: Optional[Callable[[], Sequence[api.Node]]] = None
          ) -> DeschedulerProcess:
    p = argparse.ArgumentParser(prog="koord-descheduler")
    p.add_argument("--feature-gates", default="")
    p.add_argument("--lease-file", default="koord-descheduler.lease")
    p.add_argument("--enable-leader-election", dest="leader_election",
                   action="store_true", default=True)
    p.add_argument("--disable-leader-election", dest="leader_election",
                   action="store_false")
    p.add_argument("--descheduling-interval-seconds", type=float,
                   default=120.0)
    p.add_argument("--identity", default="")
    add_metrics_flags(p)
    args = p.parse_args(argv)
    cfg = DeschedulerConfig(
        descheduling_interval_seconds=args.descheduling_interval_seconds,
        lease_file=args.lease_file,
        enable_leader_election=args.leader_election,
        feature_gates=args.feature_gates,
        identity=args.identity)
    if runner is None or get_nodes is None:
        raise SystemExit("koord-descheduler needs a CycleRunner and a node "
                         "source; pass them via build(runner=, get_nodes=)")
    return attach_metrics_server(DeschedulerProcess(cfg, runner, get_nodes), args)


def main(argv: Optional[Sequence[str]] = None,
         runner: Optional[CycleRunner] = None,
         get_nodes: Optional[Callable[[], Sequence[api.Node]]] = None) -> int:
    proc = build(argv, runner, get_nodes)
    stop = StopHandle().install_signal_handlers()
    try:
        proc.run(stop.stopped)
    finally:
        close_metrics_server(proc)
    return 0
