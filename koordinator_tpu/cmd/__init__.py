"""Process entry points — the TPU build's equivalent of the reference's
five binaries under `cmd/` (koord-scheduler, koord-manager,
koord-descheduler, koordlet, koord-runtime-proxy; SURVEY.md 2.x process
shape): argparse flags + `--feature-gates`, lease-file leader election
for the singleton control-plane processes, SIGTERM/SIGINT graceful
shutdown, and `build()` seams that let the e2e suite run the trio
in-process against fakes."""

from koordinator_tpu.cmd.runtime import (
    FileLeaseLock,
    LeaderElector,
    LeaseRecord,
    StopHandle,
    default_identity,
)

__all__ = [
    "FileLeaseLock",
    "LeaderElector",
    "LeaseRecord",
    "StopHandle",
    "default_identity",
]
