"""koord-scheduler process: hosts the SchedulerService sidecar.

Capability parity with `cmd/koord-scheduler/main.go`: flags + feature
gates, the services/metrics HTTP endpoint (frameworkext ServicesServer —
/apis/v1/plugins, /debug/flags, /metrics), optional leader election (the
reference inherits it from kube-scheduler's component config), graceful
shutdown. Scheduling itself is request-driven: the edge publishes
snapshots and feeds batches through `SchedulerService.schedule`.
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from typing import Callable, Optional, Sequence

from koordinator_tpu.cmd.runtime import (
    FileLeaseLock,
    LeaderElector,
    StopHandle,
    default_identity,
    parse_feature_gates,
)
from koordinator_tpu.features import FeatureGate, new_default_gate
from koordinator_tpu.scheduler.frameworkext import (
    SchedulerService,
    ServicesServer,
)


@dataclasses.dataclass
class SchedulerProcessConfig:
    metrics_port: int = 0            # 0 = ephemeral, -1 = disabled
    sidecar_socket: str = ""         # "" = RPC edge disabled (in-process use)
    lease_file: str = "koord-scheduler.lease"
    enable_leader_election: bool = False
    lease_duration_seconds: float = 15.0
    retry_period_seconds: float = 2.0
    feature_gates: str = ""
    identity: str = ""


class SchedulerProcess:
    def __init__(self, cfg: SchedulerProcessConfig,
                 service: Optional[SchedulerService] = None,
                 gate: Optional[FeatureGate] = None,
                 clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self.service = service or SchedulerService()
        self.gate = gate or new_default_gate()
        parse_feature_gates(self.gate, cfg.feature_gates)
        self.server: Optional[ServicesServer] = None
        if cfg.metrics_port >= 0:
            self.server = ServicesServer(self.service.registry,
                                         self.service.flags,
                                         port=cfg.metrics_port)
        self.sidecar = None
        try:
            identity = cfg.identity or default_identity()
            self.elector = LeaderElector(
                FileLeaseLock(cfg.lease_file, cfg.lease_duration_seconds),
                identity, cfg.retry_period_seconds, clock=clock)
        except BaseException:
            # a partially constructed process must not leak the already-
            # started metrics server (no handle would remain to close it)
            if self.server is not None:
                self.server.close()
            raise

    def _serve(self, should_stop: Callable[[], bool]) -> None:
        # the north-star RPC edge binds only WHILE LEADING: a standby must
        # neither serve mutating Publish/Ingest/Schedule calls (split
        # brain) nor hold the socket (it frees on step-down, letting a hot
        # standby take over the same path). The bind RETRIES while the
        # deposed leader's socket drains — failover must not crash the
        # fresh leader.
        sidecar = None
        if self.cfg.sidecar_socket:
            from koordinator_tpu.runtimeproxy.rpc import RpcError
            from koordinator_tpu.scheduler.sidecar import (
                SchedulerSidecarServer,
            )
            while not should_stop():
                try:
                    sidecar = SchedulerSidecarServer(
                        self.service, self.cfg.sidecar_socket)
                    break
                except RpcError:
                    if not self.cfg.enable_leader_election:
                        # no deposed leader will ever drain the socket:
                        # a live holder means misconfiguration — fail
                        # fast rather than silently spinning
                        raise
                    time.sleep(min(0.05, self.cfg.retry_period_seconds))
        self.sidecar = sidecar
        try:
            while not should_stop():
                time.sleep(min(0.05, self.cfg.retry_period_seconds))
        finally:
            if sidecar is not None:
                sidecar.close()
            self.sidecar = None

    def run(self, stop: Callable[[], bool]) -> None:
        try:
            if self.cfg.enable_leader_election:
                self.elector.run(self._serve, stop)
            else:
                self._serve(stop)
        finally:
            if self.server is not None:
                self.server.close()


def build(argv: Optional[Sequence[str]] = None,
          service: Optional[SchedulerService] = None) -> SchedulerProcess:
    p = argparse.ArgumentParser(prog="koord-scheduler")
    p.add_argument("--feature-gates", default="")
    p.add_argument("--metrics-port", type=int, default=0)
    p.add_argument("--sidecar-socket", default="")
    p.add_argument("--lease-file", default="koord-scheduler.lease")
    p.add_argument("--enable-leader-election", dest="leader_election",
                   action="store_true", default=False)
    p.add_argument("--identity", default="")
    args = p.parse_args(argv)
    cfg = SchedulerProcessConfig(
        metrics_port=args.metrics_port,
        sidecar_socket=args.sidecar_socket,
        lease_file=args.lease_file,
        enable_leader_election=args.leader_election,
        feature_gates=args.feature_gates,
        identity=args.identity)
    return SchedulerProcess(cfg, service)


def main(argv: Optional[Sequence[str]] = None,
         service: Optional[SchedulerService] = None) -> int:
    proc = build(argv, service)
    stop = StopHandle().install_signal_handlers()
    proc.run(stop.stopped)
    return 0
