"""koord-runtime-proxy process: CRI interposition between kubelet and the
container runtime.

Capability parity with `cmd/koord-runtime-proxy/main.go`: builds the
RuntimeProxy dispatcher over an injected backend (the real CRI client in
production, a fake in tests) and an RpcClient to the koordlet hook
socket, then idles until stopped. Flags: --runtime-hooks-endpoint (the
koordlet hook socket; the reference's RuntimeHookServerKey config) and
--hook-failure-policy Fail|Ignore."""

from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence

from koordinator_tpu.cmd.runtime import StopHandle
from koordinator_tpu.runtimeproxy.rpc import RpcClient
from koordinator_tpu.runtimeproxy.server import (
    FailurePolicy,
    RuntimeBackend,
    RuntimeProxy,
)


def build(argv: Optional[Sequence[str]] = None,
          backend: Optional[RuntimeBackend] = None) -> RuntimeProxy:
    p = argparse.ArgumentParser(prog="koord-runtime-proxy")
    p.add_argument("--runtime-hooks-endpoint",
                   default="/var/run/koordlet/koordlet.sock")
    p.add_argument("--hook-failure-policy", choices=["Fail", "Ignore"],
                   default="Ignore")
    args = p.parse_args(argv)
    if backend is None:
        raise SystemExit("koord-runtime-proxy needs a CRI backend; pass one "
                         "via build(backend=...)")
    policy = (FailurePolicy.FAIL if args.hook_failure_policy == "Fail"
              else FailurePolicy.IGNORE)
    return RuntimeProxy(backend,
                        hook_client=RpcClient(args.runtime_hooks_endpoint),
                        failure_policy=policy)


def main(argv: Optional[Sequence[str]] = None,
         backend: Optional[RuntimeBackend] = None) -> int:
    proxy = build(argv, backend)  # noqa: F841 — held live while serving
    stop = StopHandle().install_signal_handlers()
    while not stop.stopped():
        time.sleep(0.2)
    return 0
