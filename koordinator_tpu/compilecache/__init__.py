"""Contract-keyed AOT compile cache (docs/DESIGN.md "Compile cache &
columnar packing").

The koordshape contract registry already names every entry point's
shapes, dtypes and pad semantics, so the scheduler's program set is
enumerable ahead of time: `precompile` walks STRUCT_SPECS + the
contract table, materializes ShapeDtypeStruct pytrees for a configured
working set (including shrunk-mesh variants and the cascade/tail
program forms), and pre-lowers them through `CompileCache` — a manifest
layer over JAX's persistent compilation cache keyed by (contract hash,
mesh axes, jax version, backend). `counters` exposes the JAX
compilation-cache telemetry the warm-start pins assert on.

STRICTLY OPT-IN: nothing here activates by default. XLA:CPU AOT
artifacts deserialized on a different machine can segfault (the CI
hosts live-migrate — see tests/conftest.py), so a cache directory is
only ever safe same-host, and every consumer (service ctor handle,
BENCH_COMPILE_CACHE, the warm-cache smoke) passes one explicitly.
"""

from koordinator_tpu.compilecache.cache import CompileCache  # noqa: F401
from koordinator_tpu.compilecache.counters import (  # noqa: F401
    CompileWatcher,
)
from koordinator_tpu.compilecache.keys import (  # noqa: F401
    abstract_digest,
    cache_key,
    contract_fingerprint,
)
