"""The registry-walking AOT enumerator: warm the scheduler before the
cluster needs it.

Every program the scheduler can dispatch is already named by the
koordshape registry — STRUCT_SPECS declares the field layout of each
pytree struct, the contract table declares each kernel's arg specs —
so for a configured working set (P pods, N nodes, I instances, Z
zones, G gangs, ... and a device count) the whole program set is
enumerable ahead of time:

  - the flagship cycle program (core.schedule_batch, or the guarded
    fusion when the service runs guards) per cascade form;
  - the same under every plausible SHRUNK mesh (devices, devices-1,
    ..., 1) with the node axis padded to each mesh exactly as the
    service's mesh-shrink rung pads it — so device loss fails over
    onto an already-compiled program;
  - the canonical tail-compaction form (`tail_program` below: the
    device-resident adaptive tail with buffer donation threaded
    through, the same donate-(snap, counts) signature the bench jits).

`warm()` lowers + AOT-compiles each through a CompileCache; the JAX
persistent cache then serves the XLA binary to any later jit dispatch
of the same computation, so a warmed process (or a fresh process over
the same cache dir, SAME HOST) traces but never re-compiles.

`ensure_cycle_program` is the service-side entry: derive the abstract
signature from the CONCRETE cycle inputs (shapes, dtypes, committed
shardings) and ensure that one point — a dict lookup once warm.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.compilecache import keys
from koordinator_tpu.compilecache.cache import CompileCache
from koordinator_tpu.scheduler import core
from koordinator_tpu.snapshot.schema import shape_contract

# --- the canonical AOT tail form ------------------------------------------
# The bench builds its tail closure inline (sweep fused in); the
# service has no tail yet. This module-level form IS the enumerable
# tail program: schedule_batch at tail strength threaded through the
# device-resident compaction loop, with the same (snap, counts) buffer
# donation the bench's tail jits carry — donated operands alias into
# the outputs on device backends instead of doubling the snapshot's
# footprint per pass.


@shape_contract(
    snap="ClusterSnapshot",
    counts=("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
            "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
    assign="i32[P~pad:-1]", pods="PodBatch", cfg="LoadAwareConfig",
    _returns=("ClusterSnapshot",
              ("f32[SG,DM~pad:zero]", "f32[AG,DM~pad:zero]",
               "f32[AG,DM~pad:zero]", "f32[FG,DM~pad:zero]"),
              "i32[P~pad:-1]", "i32[4]"),
    _static={"tail_chunk": "TC", "min_passes": 1, "max_passes": 2,
             "tail_rounds": 2, "tail_k": 2, "cascade": False},
    _pad="delegates to core.tail_compaction_loop (same stats contract: "
         "[after_sweep, final, never_retried, passes]); counts ride "
         "COUNT_FIELDS order and pass through unchanged "
         "(charge_counts=False — the topology-free tail form)")
@functools.partial(jax.jit,
                   static_argnames=("tail_chunk", "min_passes",
                                    "max_passes", "tail_rounds",
                                    "tail_k", "cascade"),
                   donate_argnums=(0, 1))
def tail_program(snap, counts, assign, pods, cfg, *, tail_chunk: int,
                 min_passes: int, max_passes: int, tail_rounds: int = 4,
                 tail_k: int = 32, cascade: bool = False):
    """The precompilable tail: one jitted program the enumerator can
    lower for any working-set point (the bench's fused sweep+tail
    closure is shape-identical in its tail half)."""
    step = functools.partial(core.schedule_batch, num_rounds=tail_rounds,
                             k_choices=tail_k, score_dims=(0, 1),
                             tie_break=True, quota_depth=2,
                             fit_dims=(0, 1, 2, 3), cascade=cascade)
    return core.tail_compaction_loop(
        step, snap, counts, assign, pods, cfg, tail_chunk=tail_chunk,
        min_passes=min_passes, max_passes=max_passes,
        charge_counts=False)


# --- abstract-input construction from the registry ------------------------

_DTYPE_NAMES = {"f32": "float32", "i32": "int32", "i8": "int8",
                "u32": "uint32", "bool": "bool"}

# the configured working set's default dim sizes (every non-fixed
# symbol a struct field can carry); callers override the ones they
# care about (P, N, I, Z, G, devices) via WorkSet(sizes={...})
DEFAULT_SIZES = {
    "P": 256, "N": 128, "I": 2, "Z": 2, "G": 8, "Q": 8, "V": 4,
    "S": 4, "L": 4, "T": 4, "TG": 4, "SG": 1, "AG": 1, "FG": 1,
    "DM": 1, "J": 2, "K": 8, "KC": 8, "TC": 64, "RD": 4, "NS": 4,
}


def full_sizes(sizes: Dict[str, int]) -> Dict[str, int]:
    """Overlay the caller's sizes on the defaults and pin the fixed
    axes (R = NUM_RESOURCES, AGG/DEV/AX/QD module constants) — the
    same closure tools/shapecheck.py runs the contracts under."""
    from koordinator_tpu.api.extension import NUM_RESOURCES
    from koordinator_tpu.snapshot.schema import FIXED_DIMS

    out = dict(DEFAULT_SIZES)
    out.update(sizes)
    out["R"] = NUM_RESOURCES
    out.update(FIXED_DIMS)
    return out


def _parse_leaf(raw: str):
    """Minimal field-spec read (the parallel/mesh.py `_leaf_dims`
    precedent: package code re-reads the literal grammar rather than
    importing the tools/ lint tier). Returns (dtype, dims, optional)
    for a leaf spec, or None for a bare-symbol DimProp / struct ref."""
    s = raw.strip()
    optional = s.startswith("?")
    if optional:
        s = s[1:]
    if "[" not in s or not s.endswith("]"):
        return None
    dtype, rest = s.split("[", 1)
    if dtype not in _DTYPE_NAMES:
        return None
    dims: List[Any] = []
    body = rest[:-1].strip()
    if body:
        for tok in body.split(","):
            tok = tok.split("~")[0].strip()  # strip the ~pad: predicate
            dims.append(int(tok) if tok.lstrip("-").isdigit() else tok)
    return _DTYPE_NAMES[dtype], tuple(dims), optional


def _leaf_sharding(dims: tuple, mesh) -> Optional[Any]:
    """The service's mesh-shrink placement rule: node-leading axes
    shard over the mesh's node axis, everything else replicates
    (parallel/mesh.py struct_sharding, shard_pods=False)."""
    if mesh is None:
        return None
    from koordinator_tpu.parallel import mesh as meshlib

    axes = tuple(meshlib.NODE_AXIS if d == "N" else None for d in dims)
    return jax.sharding.NamedSharding(mesh,
                                      jax.sharding.PartitionSpec(*axes))


def abstract_value(raw, sizes: Dict[str, int], mesh=None,
                   materialize_optional: bool = True):
    """One field spec -> an abstract value: ShapeDtypeStruct leaves
    (sharding-annotated under a mesh), recursed structs, tuples.
    Returns the `_SKIP` sentinel for bare-symbol DimProps."""
    from koordinator_tpu.snapshot.schema import STRUCT_SPECS

    if isinstance(raw, tuple):
        return tuple(abstract_value(r, sizes, mesh, materialize_optional)
                     for r in raw)
    leaf = _parse_leaf(raw)
    if leaf is not None:
        dtype, dims, optional = leaf
        if optional and not materialize_optional:
            return None
        shape = tuple(d if isinstance(d, int) else sizes[d] for d in dims)
        sharding = _leaf_sharding(dims, mesh)
        if sharding is not None:
            return jax.ShapeDtypeStruct(shape, np.dtype(dtype),
                                        sharding=sharding)
        return jax.ShapeDtypeStruct(shape, np.dtype(dtype))
    name = raw.strip().lstrip("?")
    if name in STRUCT_SPECS:
        return abstract_struct(name, sizes, mesh, materialize_optional)
    return _SKIP


_SKIP = object()


def abstract_struct(name: str, sizes: Dict[str, int], mesh=None,
                    materialize_optional: bool = True):
    """Registry walk: STRUCT_SPECS[name] -> an abstract struct instance
    whose leaves are ShapeDtypeStructs sized by the working set (bare
    dim symbols are symbolic-int properties, never constructor
    fields — the shapecheck Tier-B rule)."""
    from koordinator_tpu.snapshot.schema import STRUCT_CLASSES, STRUCT_SPECS

    cls = STRUCT_CLASSES[name]
    kwargs = {}
    for fname, raw in STRUCT_SPECS[name].items():
        v = abstract_value(raw, sizes, mesh, materialize_optional)
        if v is _SKIP:
            continue
        kwargs[fname] = v
    return cls(**kwargs)


def abstract_from_example(tree):
    """Concrete cycle inputs -> the same pytree of ShapeDtypeStructs,
    preserving committed shardings (64-bit host leaves canonicalize to
    the 32-bit layout jit would give them)."""
    from jax import dtypes as jax_dtypes

    def to_sds(x):
        dt = getattr(x, "dtype", None)
        if dt is None:
            dt = np.asarray(x).dtype
        dt = jax_dtypes.canonicalize_dtype(np.dtype(dt))
        sharding = getattr(x, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            return jax.ShapeDtypeStruct(np.shape(x), dt,
                                        sharding=sharding)
        return jax.ShapeDtypeStruct(np.shape(x), dt)

    return jax.tree_util.tree_map(to_sds, tree)


def mesh_axes_of(tree) -> Optional[Dict[str, int]]:
    """The mesh axis sizes any sharded leaf of `tree` was committed
    under, or None (single-device / host inputs)."""
    from koordinator_tpu.parallel import mesh as meshlib

    for leaf in jax.tree_util.tree_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            return meshlib.mesh_axis_sizes(sharding.mesh)
    return None


# --- the working set + enumeration ----------------------------------------

# the service's cycle-program static defaults (SchedulerService passes
# its schedule_kwargs verbatim; these mirror the smoke/test settings)
DEFAULT_STATICS = {"num_rounds": 2, "k_choices": 4}
DEFAULT_TAIL = {"tail_chunk": 64, "min_passes": 2, "max_passes": 6,
                "tail_rounds": 4, "tail_k": 32, "cascade": False}


@dataclasses.dataclass
class WorkSet:
    """One configured (P, N, I, Z, G, ..., devices) working set to
    pre-lower. `devices` enumerates the shrunk-mesh ladder d, d-1,
    ..., 1; `cascade_forms` enumerates the cascade on/off program
    pair; `tail` configures the canonical tail form (None skips it);
    `guards` lowers the guarded fusion instead of the bare kernel."""

    sizes: Dict[str, int] = dataclasses.field(default_factory=dict)
    statics: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_STATICS))
    devices: int = 1
    cascade_forms: Tuple[bool, ...] = (False, True)
    tail: Optional[Dict[str, Any]] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_TAIL))
    guards: bool = False
    materialize_optional: bool = True


@dataclasses.dataclass
class ProgramSpec:
    """One enumerated (program, working-set point): a label, the
    manifest cache key, the AOT build thunk, and manifest metadata."""

    label: str
    key: str
    build: Callable[[], Any]
    meta: Dict[str, Any]


def _cycle_callable(guarded: bool):
    if guarded:
        from koordinator_tpu.scheduler import guards
        return guards.guarded_schedule_batch, "guarded_schedule_batch"
    return core.schedule_batch, "schedule_batch"


def enumerate_programs(ws: WorkSet,
                       fingerprint: Optional[str] = None
                       ) -> List[ProgramSpec]:
    """Walk the registry for every (mesh rung x program form) of the
    working set. Meshes are built over the first d visible devices —
    the same `jax.devices()[:d]` prefix the service's mesh-shrink rung
    rebuilds over."""
    from koordinator_tpu.parallel import mesh as meshlib

    if fingerprint is None:
        fingerprint = keys.contract_fingerprint()
    fn, fn_label = _cycle_callable(ws.guards)
    visible = jax.devices()
    max_d = max(min(ws.devices, len(visible)), 1)
    specs: List[ProgramSpec] = []
    for d in range(max_d, 0, -1):
        mesh = meshlib.make_mesh(visible[:d]) if d > 1 else None
        sizes = full_sizes(ws.sizes)
        if mesh is not None:
            # the mesh-shrink rung pads the node axis to the shrunk
            # mesh before resharding — enumerate the PADDED shape
            sizes["N"] = meshlib.padded_node_count(sizes["N"], mesh)
        mesh_axes = meshlib.mesh_axis_sizes(mesh) if mesh else None
        snap_sds = abstract_struct("ClusterSnapshot", sizes, mesh,
                                   ws.materialize_optional)
        pods_sds = abstract_struct("PodBatch", sizes, None,
                                   ws.materialize_optional)
        cfg_sds = abstract_struct("LoadAwareConfig", sizes, None,
                                  ws.materialize_optional)
        for cascade in ws.cascade_forms:
            statics = dict(ws.statics, cascade=cascade)
            label = (f"{fn_label}/devices={d}/cascade="
                     f"{'on' if cascade else 'off'}")
            specs.append(ProgramSpec(
                label=label,
                key=keys.cache_key(
                    label, keys.abstract_digest(
                        (snap_sds, pods_sds, cfg_sds)),
                    statics, mesh_axes, fingerprint=fingerprint),
                build=functools.partial(
                    _build_cycle, fn, snap_sds, pods_sds, cfg_sds,
                    statics),
                meta={"form": "cycle", "devices": d,
                      "cascade": cascade, "sizes_P": sizes["P"],
                      "sizes_N": sizes["N"]}))
        if ws.tail is not None:
            tail_statics = dict(DEFAULT_TAIL, **ws.tail)
            tail_statics["tail_chunk"] = min(tail_statics["tail_chunk"],
                                             sizes["P"])
            counts_sds = tuple(getattr(pods_sds, f)
                               for f in core.COUNT_FIELDS)
            assign_sds = jax.ShapeDtypeStruct((sizes["P"],),
                                              np.dtype("int32"))
            label = f"tail_program/devices={d}"
            specs.append(ProgramSpec(
                label=label,
                key=keys.cache_key(
                    label, keys.abstract_digest(
                        (snap_sds, counts_sds, assign_sds, pods_sds,
                         cfg_sds)),
                    tail_statics, mesh_axes, fingerprint=fingerprint),
                build=functools.partial(
                    _build_tail, snap_sds, counts_sds, assign_sds,
                    pods_sds, cfg_sds, tail_statics),
                meta={"form": "tail", "devices": d,
                      "sizes_P": sizes["P"], "sizes_N": sizes["N"]}))
    return specs


def _build_cycle(fn, snap_sds, pods_sds, cfg_sds, statics):
    return fn.lower(snap_sds, pods_sds, cfg_sds, **statics).compile()


def _build_tail(snap_sds, counts_sds, assign_sds, pods_sds, cfg_sds,
                statics):
    return tail_program.lower(snap_sds, counts_sds, assign_sds,
                              pods_sds, cfg_sds, **statics).compile()


def warm(cache: CompileCache, ws: WorkSet, metrics=None,
         log_fn: Optional[Callable[[str], None]] = None) -> dict:
    """Pre-lower + AOT-compile the working set through the cache.
    Activates the cache (opt-in happened when the caller built one).
    Observes per-program wall time on `metrics.precompile_seconds`
    when a SchedulerMetrics catalog is passed."""
    cache.activate()
    report = {"programs": 0, "hit": 0, "warm": 0, "miss": 0,
              "seconds": 0.0}
    for spec in enumerate_programs(ws, fingerprint=cache.fingerprint):
        t0 = time.perf_counter()
        status = cache.ensure(spec.label, spec.build, key=spec.key,
                              meta=spec.meta)
        dt = time.perf_counter() - t0
        if metrics is not None:
            metrics.precompile_seconds.observe(dt)
        report["programs"] += 1
        report[status] += 1
        report["seconds"] += dt
        if log_fn is not None:
            log_fn(f"precompile: {status:<4s} {spec.label} "
                   f"({dt:.2f}s)")
    report["seconds"] = round(report["seconds"], 3)
    return report


# --- the service-side ensure ----------------------------------------------

def ensure_cycle_program(cache: CompileCache, snap, pods, cfg,
                         statics: Dict[str, Any], *, guarded: bool,
                         metrics=None) -> str:
    """Warm exactly the program the service is about to dispatch:
    abstract signature from the CONCRETE inputs (padded/sharded forms
    included), keyed like the enumerator. A dict lookup once warm —
    the ensure path costs one lower+compile per NEW working-set point
    and nothing after."""
    fn, fn_label = _cycle_callable(guarded)
    sds = abstract_from_example((snap, pods, cfg))
    key = keys.cache_key(fn_label, keys.abstract_digest(sds), statics,
                         mesh_axes_of(sds),
                         fingerprint=cache.fingerprint)
    status = cache.ensure(
        fn_label, functools.partial(_build_cycle, fn, *sds, statics),
        key=key, meta={"form": "cycle", "source": "service"})
    if metrics is not None:
        if status == "miss":
            metrics.compile_cache_misses.inc()
        else:
            metrics.compile_cache_hits.inc()
    return status
