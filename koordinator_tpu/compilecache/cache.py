"""CompileCache: a manifest layer over JAX's persistent compilation
cache.

JAX's persistent cache already keys serialized XLA executables on the
computation itself (HLO + compile options + backend fingerprint) — a
correct but OPAQUE store: nothing in it says which scheduler program a
blob belongs to, which contract revision produced it, or whether a
spec edit stranded it. The manifest adds that provenance: one JSON
entry per (program, working-set point) cache key
(keys.cache_key: contract fingerprint x abstract inputs x statics x
mesh axes x jax version x backend), so

  - a contract/spec change invalidates exactly the affected entries
    (every entry whose recorded fingerprint no longer matches), loudly;
  - a jax upgrade or backend switch drops the whole entry set, loudly;
  - a corrupt manifest is set aside and rebuilt, loudly — a cache that
    cannot prove provenance serves nothing.

The underlying XLA blobs are left to JAX's own store either way: a
dropped manifest entry merely costs a re-lower (the persistent cache
then usually still hits on the unchanged HLO); a WRONG manifest entry
would claim warmth the contracts no longer back.

STRICTLY OPT-IN, SAME-HOST ONLY: activate() flips the process-global
jax_compilation_cache_dir. XLA:CPU artifacts deserialized on a
different machine can segfault (live-migrating CI hosts — see
tests/conftest.py), so never ship a cache dir across machines.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from koordinator_tpu.compilecache import counters, keys

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1


def _reset_jax_persistent_cache() -> None:
    """Drop JAX's once-per-process persistent-cache singleton so the
    next compile re-reads jax_compilation_cache_dir. Private API, so
    absence is tolerated — the cost is only that a pre-activate compile
    pins the old dir (warmth degrades, correctness doesn't)."""
    try:
        from jax._src import compilation_cache as jax_cc
        jax_cc.reset_cache()
    except Exception:  # pragma: no cover - jax internals moved
        log.warning("compilecache: could not reset jax persistent-cache "
                    "singleton; pre-activate compiles may pin a stale dir",
                    exc_info=True)


class CompileCache:
    """An opt-in, same-host compile cache handle.

    `activate()` points JAX's persistent compilation cache at `path`
    (clamping the min-compile-time/min-entry-size thresholds so even
    small CPU test programs persist) and loads the manifest. `ensure()`
    runs an AOT build (lower+compile) exactly once per cache key —
    in-memory memo first, then the persistent cache absorbs the XLA
    compile — and records the entry. `hits`/`misses` mirror onto the
    scheduler metrics when a catalog is attached.
    """

    def __init__(self, path: str,
                 fingerprint: Optional[str] = None) -> None:
        self.path = path
        self.fingerprint = (fingerprint if fingerprint is not None
                            else keys.contract_fingerprint())
        self.active = False
        self.hits = 0
        self.misses = 0
        # provenance of every loudly-dropped entry/file: (key-or-path,
        # reason) — tests pin that invalid state lands HERE, never in
        # `manifest["entries"]`
        self.discarded: List[tuple] = []
        self._programs: Dict[str, Any] = {}
        self.manifest: Dict[str, Any] = self._fresh_manifest()

    # --- manifest ---------------------------------------------------------

    def _fresh_manifest(self) -> Dict[str, Any]:
        import jax

        return {
            "version": MANIFEST_VERSION,
            "fingerprint": self.fingerprint,
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "entries": {},
        }

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.path, MANIFEST_NAME)

    def _discard(self, what: str, reason: str) -> None:
        self.discarded.append((what, reason))
        log.warning("compilecache: discarding %s: %s", what, reason)

    def _load_manifest(self) -> None:
        import jax

        fresh = self._fresh_manifest()
        try:
            with open(self.manifest_path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            self.manifest = fresh
            return
        except (json.JSONDecodeError, OSError, UnicodeDecodeError) as exc:
            # corrupt: set the file aside (post-mortem evidence) and
            # rebuild — NEVER serve an entry whose provenance is
            # unreadable
            aside = self.manifest_path + f".corrupt.{os.getpid()}"
            try:
                os.replace(self.manifest_path, aside)
            except OSError:
                aside = "<unrenameable>"
            self._discard(self.manifest_path,
                          f"corrupt manifest ({exc!r}); moved to {aside}, "
                          "rebuilding empty")
            self.manifest = fresh
            return
        if not isinstance(raw, dict) or \
                raw.get("version") != MANIFEST_VERSION or \
                not isinstance(raw.get("entries"), dict):
            self._discard(self.manifest_path,
                          "unrecognized manifest schema; rebuilding empty")
            self.manifest = fresh
            return
        kept: Dict[str, Any] = {}
        for key, entry in raw["entries"].items():
            if not isinstance(entry, dict):
                self._discard(key, "malformed entry (not a mapping)")
                continue
            stale = []
            if entry.get("fingerprint") != self.fingerprint:
                stale.append("contract fingerprint changed")
            if entry.get("jax_version") != jax.__version__:
                stale.append(f"jax {entry.get('jax_version')} -> "
                             f"{jax.__version__}")
            if entry.get("backend") != jax.default_backend():
                stale.append(f"backend {entry.get('backend')} -> "
                             f"{jax.default_backend()}")
            if stale:
                self._discard(key, "stale entry (" + "; ".join(stale) + ")")
                continue
            kept[key] = entry
        self.manifest = dict(fresh, entries=kept)

    def _save_manifest(self) -> None:
        # atomic publish: a crash mid-write must leave either the old
        # manifest or the new one, never a torn file (the corrupt path
        # above exists for external corruption, not our own writes)
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)

    # --- lifecycle --------------------------------------------------------

    def activate(self) -> "CompileCache":
        """Point the process at this cache dir and load the manifest.
        Idempotent. Opt-in by construction: only an explicit activate()
        ever touches the process-global persistent-cache config."""
        if self.active:
            return self
        import jax

        os.makedirs(self.path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", self.path)
        # persist EVERYTHING: the scheduler's small CPU-test programs
        # compile in well under the default 1s threshold, and a warmer
        # that silently skips them pins nothing
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # JAX latches the persistent cache at the FIRST compile of the
        # process: if anything compiled before activate() (even a bare
        # jnp op building a snapshot), the dir change above is silently
        # ignored forever. Reset so the next compile re-initializes
        # against this dir.
        _reset_jax_persistent_cache()
        counters.install()
        self._load_manifest()
        self.active = True
        return self

    def deactivate(self) -> None:
        """Detach the process-global persistent cache (tests; the
        on-disk state stays for the next activate())."""
        if not self.active:
            return
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
        _reset_jax_persistent_cache()
        self.active = False

    # --- the warm path ----------------------------------------------------

    def lookup(self, key: str) -> Optional[dict]:
        """The manifest entry for `key`, or None. Only entries that
        survived provenance validation at load time exist here — a
        stale/corrupt entry can never be returned."""
        return self.manifest["entries"].get(key)

    def ensure(self, program: str, build: Callable[[], Any], *,
               key: str, meta: Optional[dict] = None) -> str:
        """Make `program`'s executable warm for this working-set point.

        Returns the outcome:
          "hit"  — already ensured this process (in-memory memo);
          "warm" — built, but the XLA compile was absorbed by the
                   persistent cache (cache_misses == 0 with hits);
          "miss" — built with at least one real XLA compilation.
        "hit"/"warm" count as cache hits, "miss" as a miss.
        """
        if key in self._programs:
            self.hits += 1
            return "hit"
        import jax

        t0 = time.perf_counter()
        with counters.watch() as w:
            exe = build()
        elapsed = time.perf_counter() - t0
        if self.active and w.cache_misses == 0 and w.cache_hits > 0:
            status = "warm"
            self.hits += 1
        else:
            status = "miss"
            self.misses += 1
        self._programs[key] = exe
        self.manifest["entries"][key] = {
            "program": program,
            "fingerprint": self.fingerprint,
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "status": status,
            "ensure_seconds": round(elapsed, 4),
            "compile_seconds": round(w.compile_seconds, 4),
            **(meta or {}),
        }
        if self.active:
            self._save_manifest()
        return status

    def stats(self) -> dict:
        return {
            "path": self.path,
            "active": self.active,
            "hits": self.hits,
            "misses": self.misses,
            "entries": len(self.manifest["entries"]),
            "discarded": len(self.discarded),
            "fingerprint": self.fingerprint[:16],
        }
