"""JAX compilation telemetry, scoped: the warm-start pins' source of
truth.

jax.monitoring has no unregister, so ONE pair of process-global
listeners installs idempotently on first use and feeds module-global
tallies; `CompileWatcher` snapshots them around a region and exposes
the deltas. The pin that matters (tests, the warm-cache smoke, bench's
`cache=` stamp) is `cache_misses == 0`: with a persistent cache dir
active, `/jax/compilation_cache/cache_misses` fires exactly when XLA
actually compiled, while the backend_compile duration event fires even
on a persistent-cache HIT (it times compile-OR-retrieve) — so compile
durations measure cost, never prove absence of compilation.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

from koordinator_tpu.utils.sync import guard_module

# the event names jax 0.4.x emits (jax/_src/compiler.py,
# jax/_src/compilation_cache.py); pinned by tests/test_compilecache.py
EVENT_CACHE_HIT = "/jax/compilation_cache/cache_hits"
EVENT_CACHE_MISS = "/jax/compilation_cache/cache_misses"
DURATION_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
DURATION_TRACE = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_counts: collections.Counter = collections.Counter()
_durations: Dict[str, float] = collections.defaultdict(float)
_installed = False
guard_module(__name__, _counts="_lock", _durations="_lock",
             _installed="_lock")


def _on_event(event: str, **_kw) -> None:
    with _lock:
        _counts[event] += 1


def _on_duration(event: str, duration: float, **_kw) -> None:
    with _lock:
        _counts[event] += 1
        _durations[event] += float(duration)


def install() -> None:
    """Idempotently install the process-global listeners. Safe to call
    any number of times; never installs twice (jax.monitoring keeps
    listeners forever, so a second registration would double-count)."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)


def snapshot() -> tuple:
    """(counts, duration sums) copies of the global tallies."""
    with _lock:
        return dict(_counts), dict(_durations)


class CompileWatcher:
    """Context manager exposing the compilation telemetry deltas of its
    region: `cache_hits` / `cache_misses` (persistent-cache events —
    both 0 when no cache dir is configured), `backend_compiles` and
    `compile_seconds` (compile-or-retrieve invocations and their summed
    wall time), `trace_seconds`. Readable live inside the region and
    frozen after exit."""

    def __init__(self) -> None:
        self._c0: Dict[str, int] = {}
        self._d0: Dict[str, float] = {}
        self._c1: Optional[Dict[str, int]] = None
        self._d1: Optional[Dict[str, float]] = None

    def __enter__(self) -> "CompileWatcher":
        install()
        self._c0, self._d0 = snapshot()
        self._c1 = self._d1 = None
        return self

    def __exit__(self, *_exc) -> None:
        self._c1, self._d1 = snapshot()

    def _count(self, event: str) -> int:
        now = self._c1 if self._c1 is not None else snapshot()[0]
        return now.get(event, 0) - self._c0.get(event, 0)

    def _duration(self, event: str) -> float:
        now = self._d1 if self._d1 is not None else snapshot()[1]
        return now.get(event, 0.0) - self._d0.get(event, 0.0)

    @property
    def cache_hits(self) -> int:
        return self._count(EVENT_CACHE_HIT)

    @property
    def cache_misses(self) -> int:
        return self._count(EVENT_CACHE_MISS)

    @property
    def backend_compiles(self) -> int:
        return self._count(DURATION_BACKEND_COMPILE)

    @property
    def compile_seconds(self) -> float:
        return self._duration(DURATION_BACKEND_COMPILE)

    @property
    def trace_seconds(self) -> float:
        return self._duration(DURATION_TRACE)


def watch() -> CompileWatcher:
    """`with counters.watch() as w:` sugar."""
    return CompileWatcher()
