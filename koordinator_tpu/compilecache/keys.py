"""Cache-key derivation for the AOT compile cache.

A cached executable is only reusable while everything that shaped its
HLO is unchanged. The key therefore folds together:

  - the CONTRACT FINGERPRINT: a digest of the whole koordshape registry
    (every contract's arg/return/static/callable/pad specs plus every
    registered struct's field specs). Editing any spec string — a dim
    symbol, a pad predicate, a field dtype — changes the fingerprint
    and hence every key, so a contract change can never serve a stale
    program. This is deliberately coarser than per-entry invalidation
    of the underlying XLA artifacts (JAX's persistent cache keys those
    on the HLO itself); the manifest layer uses the fingerprint to
    decide which of ITS entries are still trustworthy.
  - the ABSTRACT SIGNATURE of the inputs: every leaf's path, shape,
    dtype and (when committed) sharding spec.
  - the STATIC ARGUMENTS, canonically serialized.
  - the MESH AXES the program was lowered for (None on single device).
  - the jax version and backend: an executable is never portable
    across either.

Pure derivation, no I/O; `cache.CompileCache` owns persistence.
"""

from __future__ import annotations

import hashlib
import importlib
from typing import Any, Dict, Mapping, Optional

# every module that registers contracts or structs: the fingerprint
# must digest the FULLY populated registry, not whatever the caller
# happened to import first (two processes warming different subsets
# would otherwise derive different fingerprints for the same code).
# Mirrors tools/shapecheck.py CONTRACT_MODULES; tests pin the two in
# sync.
CONTRACT_MODULES = (
    "koordinator_tpu.snapshot.schema",
    "koordinator_tpu.snapshot.delta",
    "koordinator_tpu.ops.feasibility",
    "koordinator_tpu.ops.waterfill",
    "koordinator_tpu.ops.quota_demand",
    "koordinator_tpu.scheduler.cascade",
    "koordinator_tpu.scheduler.core",
    "koordinator_tpu.scheduler.guards",
    "koordinator_tpu.compilecache.precompile",
    "koordinator_tpu.parallel.shardops",
    "koordinator_tpu.scheduler.plugins.loadaware",
    "koordinator_tpu.scheduler.plugins.deviceshare",
    "koordinator_tpu.scheduler.plugins.numaaware",
    "koordinator_tpu.descheduler.lownodeload_device",
    "koordinator_tpu.slo_controller.noderesource",
)


def _canon(value: Any) -> str:
    """Deterministic serialization for static argument values and spec
    tables (sorted mappings/sets so dict order can't leak into keys)."""
    if isinstance(value, Mapping):
        items = ", ".join(f"{_canon(k)}: {_canon(value[k])}"
                          for k in sorted(value, key=repr))
        return "{" + items + "}"
    if isinstance(value, (set, frozenset)):
        return "{" + ", ".join(sorted(_canon(v) for v in value)) + "}"
    if isinstance(value, (list, tuple)):
        body = ", ".join(_canon(v) for v in value)
        return ("[" if isinstance(value, list) else "(") + body + \
            ("]" if isinstance(value, list) else ")")
    if callable(value):
        # a callable static (step_fn) keys on its dotted name, not its
        # repr (which carries the object address and would bust the
        # cache every process)
        mod = getattr(value, "__module__", "?")
        name = getattr(value, "__qualname__",
                       getattr(value, "__name__", repr(value)))
        return f"<callable {mod}.{name}>"
    return repr(value)


def contract_fingerprint(contracts: Optional[Mapping] = None,
                         structs: Optional[Mapping] = None) -> str:
    """sha256 over the canonical serialization of the contract registry
    (SHAPE_CONTRACTS) + the struct field specs (STRUCT_SPECS).

    `contracts`/`structs` default to the live registry; tests pass
    doctored copies to pin that mutating a spec string or a field dtype
    changes the fingerprint (and hence every cache key).
    """
    if contracts is None or structs is None:
        for mod in CONTRACT_MODULES:
            importlib.import_module(mod)
        from koordinator_tpu.snapshot import schema
        if contracts is None:
            contracts = schema.SHAPE_CONTRACTS
        if structs is None:
            structs = schema.STRUCT_SPECS
    parts = []
    for key in sorted(contracts):
        c = contracts[key]
        parts.append(f"contract {key}")
        for a in sorted(c.args):
            parts.append(f"  arg {a} = {_canon(c.args[a])}")
        parts.append(f"  returns {_canon(c.returns)}")
        for s in sorted(c.static):
            parts.append(f"  static {s} = {_canon(c.static[s])}")
        for s in sorted(c.callables):
            parts.append(f"  callable {s} = {_canon(c.callables[s])}")
        parts.append(f"  pad {c.pad!r}")
    for name in sorted(structs):
        parts.append(f"struct {name}")
        for fname in sorted(structs[name]):
            parts.append(f"  field {fname} = "
                         f"{_canon(structs[name][fname])}")
    blob = "\n".join(parts).encode()
    return hashlib.sha256(blob).hexdigest()


def abstract_digest(tree: Any) -> str:
    """Stable digest of an abstract input pytree: every leaf's tree
    path, shape, dtype, and sharding spec (committed arrays and
    sharding-annotated ShapeDtypeStructs carry one; host values
    don't)."""
    import jax

    parts = []
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    parts.append(f"treedef {treedef}")
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", None)
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        parts.append(f"{jax.tree_util.keystr(path)}: shape={shape} "
                     f"dtype={dtype} spec={spec}")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


def cache_key(program: str, inputs_digest: str,
              statics: Optional[Dict[str, Any]] = None,
              mesh_axes: Optional[Dict[str, int]] = None,
              backend: Optional[str] = None,
              jax_version: Optional[str] = None,
              fingerprint: Optional[str] = None) -> str:
    """The manifest key for one (program, working-set point): sha256
    over program name, input signature, canonical statics, mesh axes,
    backend, jax version, and the contract fingerprint."""
    import jax

    if backend is None:
        backend = jax.default_backend()
    if jax_version is None:
        jax_version = jax.__version__
    if fingerprint is None:
        fingerprint = contract_fingerprint()
    blob = "\n".join([
        f"program {program}",
        f"inputs {inputs_digest}",
        f"statics {_canon(dict(statics or {}))}",
        f"mesh {_canon(dict(mesh_axes) if mesh_axes else None)}",
        f"backend {backend}",
        f"jax {jax_version}",
        f"contracts {fingerprint}",
    ]).encode()
    return hashlib.sha256(blob).hexdigest()
