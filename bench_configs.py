"""BASELINE measurement configs 1-5 (BASELINE.md "Measurement configs").

The north-star bench (bench.py) measures config 0 (100k x 10k, LoadAware+
quota). This file measures the remaining named configs so every path has
a recorded scale number:
  1. spark colocation: 32 BE pods x 10 nodes, LoadAware score only
  2. 10k pods x 1k nodes, LoadAware + NodeNUMAResource (enable_numa)
  3. coscheduling: 1k strict gangs (8 pods each) x 5k nodes
  4. ElasticQuota fair-share: 500-quota tree, 50k pending pods
  5. descheduler LowNodeLoad: 10k-node eviction/migration plan

Prints one JSON line per measured path ({"metric": ..., "value":
<seconds>, "unit": "s", ...}); config 5 emits TWO lines (the uncapped
prefix-kernel plan and the capped scan-kernel plan).
The reference publishes no numbers for these paths (BASELINE.md), so
there is no vs_baseline; the lines exist to make regressions visible
round over round.
"""

import dataclasses
import functools
import os
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _use_approx() -> bool:
    """Shared with bench.py: candidate selection is EXACT by default
    since round 5 (hardware-measured faster); BENCH_APPROX=1 opts into
    approx_max_k, and every emitted line records the mode."""
    return os.environ.get("BENCH_APPROX", "0") not in ("0", "false", "")


def _emit(name, elapsed, **extra):
    from koordinator_tpu.utils.hostinfo import host_fields
    out = {"metric": name, "value": round(elapsed, 4), "unit": "s"}
    out.update(extra)
    out.update(host_fields())
    out.setdefault("platform", jax.devices()[0].platform)
    out.setdefault("approx_topk", _use_approx())
    print(json.dumps(out))


def _run_scheduler_config(name, snap, pods, cfg, chunk, **kw):
    """Warm + time a chunked schedule over the batch (the bench sweep
    shape: lax.scan over [C, CHUNK, ...] pod columns, one readback)."""
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.utils import synthetic

    num_pods = pods.valid.shape[0]
    stacked = synthetic.stack_pod_chunks(pods, chunk)
    step = functools.partial(core.schedule_batch, num_rounds=2, k_choices=8,
                             score_dims=(0, 1), approx_topk=_use_approx(),
                             tie_break=True, quota_depth=2,
                             fit_dims=(0, 1, 2, 3), **kw)

    @jax.jit
    def sweep(snap, stacked, pods_dev, cfg):
        def body(s, cols):
            res = step(s, pods_dev.replace(**cols), cfg)
            return res.snapshot, res.assignment
        s, assign = jax.lax.scan(body, snap, stacked)
        return s, assign.reshape(-1)

    snap_dev = jax.device_put(snap)
    stacked = jax.device_put(stacked)
    pods_dev = jax.device_put(pods)
    cfg = jax.device_put(cfg)
    _, a = sweep(snap_dev, stacked, pods_dev, cfg)   # warm/compile
    np.asarray(a)
    t0 = time.perf_counter()
    _, a = sweep(snap_dev, stacked, pods_dev, cfg)
    a = np.asarray(a)
    elapsed = time.perf_counter() - t0
    _emit(name, elapsed, pods=num_pods, placed=int((a >= 0).sum()),
          pods_per_sec=round(num_pods / elapsed))
    return a


def config_1_spark():
    """32 BE pods x 10 nodes, LoadAware score only (examples/spark-jobs)."""
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    snap = synthetic.synthetic_cluster(10, num_quotas=2, seed=0)
    pods = synthetic.synthetic_pods(32, seed=1, prod_frac=0.0, num_quotas=2)
    _run_scheduler_config("baseline_cfg1_spark_32x10", snap, pods,
                          LoadAwareConfig.make(), chunk=32,
                          enable_numa=False)


def config_2_numa():
    """10k pods x 1k nodes with NodeNUMAResource engaged: nodes carry two
    populated NUMA zones; prod pods are single-NUMA bound (the resource-
    spec annotation + LSR QoS path)."""
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    snap = synthetic.with_two_numa_zones(
        synthetic.synthetic_cluster(1000, num_quotas=32, seed=0))

    pods = synthetic.synthetic_pods(10_000, seed=1, prod_frac=0.6,
                                    num_quotas=32)
    # prod pods are the CPU-bound tier (requests in native cpu/mem dims)
    numa_single = np.asarray(pods.priority_class) == 4
    pods = pods.replace(numa_single=jnp.asarray(numa_single))
    _run_scheduler_config("baseline_cfg2_numa_10kx1k", snap, pods,
                          LoadAwareConfig.make(), chunk=2000,
                          enable_numa=True)


def config_3_gangs():
    """1k strict gangs x 8 members against 5k nodes, all-or-nothing."""
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    snap = synthetic.synthetic_cluster(5000, num_quotas=32, seed=0,
                                       num_gangs=1000, max_gangs=1024,
                                       gang_min_member=8)
    pods = synthetic.synthetic_pods(8000, seed=1, num_quotas=32,
                                    num_gangs=1000, gang_min_member=8)
    _run_scheduler_config("baseline_cfg3_gangs_1kx8_5k", snap, pods,
                          LoadAwareConfig.make(), chunk=2000,
                          enable_numa=False)


def config_4_quota():
    """500-quota hierarchical tree, 50k pending pods."""
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    snap = synthetic.synthetic_cluster(5000, num_quotas=500, max_quotas=512,
                                       seed=0)
    pods = synthetic.synthetic_pods(50_000, seed=1, num_quotas=500)
    _run_scheduler_config("baseline_cfg4_quota_500x50k", snap, pods,
                          LoadAwareConfig.make(), chunk=2500,
                          enable_numa=False)


def config_5_descheduler():
    """LowNodeLoad rebalance plan over 10k nodes: classification +
    budgeted eviction selection as ONE jitted program (the prefix-sum
    formulation in descheduler/lownodeload_device.py; golden-equal to
    the host loop per tests/test_descheduler_device.py)."""
    from koordinator_tpu.api import types as api
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.descheduler import (
        DeviceLowNodeLoad,
        EvictionLimiter,
        LowNodeLoadArgs,
        RecordingEvictor,
    )

    rng = np.random.default_rng(3)
    now = 1e9
    n = 10_000
    nodes, metrics, pods_by_node = [], {}, {}
    usage_frac = rng.uniform(0.1, 0.95, size=n)
    for i in range(n):
        name = f"n{i}"
        nodes.append(api.Node(meta=api.ObjectMeta(name=name),
                              allocatable={RK.CPU: 64000.0,
                                           RK.MEMORY: 262144.0}))
        metrics[name] = api.NodeMetric(
            node_name=name, update_time=now,
            node_usage={RK.CPU: 64000.0 * usage_frac[i],
                        RK.MEMORY: 262144.0 * usage_frac[i]})
        if usage_frac[i] > 0.7:  # candidates carry evictable pods
            # node_name matters: the EvictionLimiter keys its per-node
            # counts on it (a pod without one would collapse every pod
            # into a single "" bucket under per-node caps)
            pods_by_node[name] = [
                api.Pod(meta=api.ObjectMeta(name=f"{name}-p{j}",
                                            uid=f"{name}-p{j}"),
                        priority=5500, qos_label="BE", node_name=name,
                        requests={RK.CPU: 4000.0, RK.MEMORY: 8192.0})
                for j in range(4)]

    args = LowNodeLoadArgs(consecutive_abnormalities=1)

    def measure(evictor, metric, **extra):
        plugin = DeviceLowNodeLoad(args, evictor)
        plugin.balance_once(nodes, metrics, pods_by_node, now)  # warm
        evictor.limiter.reset()
        evictor.evictions.clear()  # the warm plan must not double-count
        t0 = time.perf_counter()
        plugin.balance_once(nodes, metrics, pods_by_node, now)
        _emit(metric, time.perf_counter() - t0, nodes=n,
              evictions_planned=len(evictor.evictions),
              device_plan=True, **extra)

    measure(RecordingEvictor(), "baseline_cfg5_descheduler_10k")
    # the CAPPED variant (per-node/per-namespace/per-cycle limits — the
    # production blast-radius configuration, round 5): the lax.scan
    # kernel replaces the prefix kernel; this line keeps its latency
    # regression-visible round over round
    measure(RecordingEvictor(EvictionLimiter(
        max_per_cycle=4000, max_per_node=2, max_per_namespace=2000)),
        "baseline_cfg5_descheduler_10k_capped",
        caps="node=2,ns=2000,cycle=4000")


def main():
    config_1_spark()
    config_2_numa()
    config_3_gangs()
    config_4_quota()
    config_5_descheduler()


if __name__ == "__main__":
    import bench

    bench.ensure_platform()
    main()
